"""Expression engine tests: device (jnp, jitted) vs host (numpy) backends
must agree with each other and with independently-computed expected values —
the same CPU-vs-accelerator philosophy as the reference's integration tests
(``asserts.py assert_gpu_and_cpu_are_equal_collect``)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.types as T
from spark_rapids_tpu.columnar import arrow_to_device, device_column_to_arrow
from spark_rapids_tpu.sql.expressions import (AttributeReference, Literal,
                                              bind_references)
from spark_rapids_tpu.sql.expressions.core import EvalContext
from spark_rapids_tpu.sql.expressions import arithmetic as A
from spark_rapids_tpu.sql.expressions import cast as C
from spark_rapids_tpu.sql.expressions import conditional as Cond
from spark_rapids_tpu.sql.expressions import hashing as H
from spark_rapids_tpu.sql.expressions import math_fns as M
from spark_rapids_tpu.sql.expressions import predicates as P


def make_batch(table: pa.Table):
    return arrow_to_device(table)


def to_host_batch(batch):
    """Same layout (encoded columns included), numpy arrays (host engine
    input) — a pytree fetch, so it doesn't care which DeviceColumn
    representation each column uses."""
    import jax
    return jax.device_get(batch)


def attr(name, dtype):
    return AttributeReference(name, dtype)


def eval_both(expr, table: pa.Table):
    """Evaluate on device (through jit) and host; assert equal; return host
    pylist."""
    batch = make_batch(table)
    attrs = [AttributeReference(n, c.dtype) for n, c in
             zip(batch.names, batch.columns)]
    bound = bind_references(expr, attrs)

    # host path
    hb = to_host_batch(batch)
    with np.errstate(all="ignore"):
        hcol = bound.eval(EvalContext(hb, xp=np))
    n = table.num_rows
    host_vals = device_column_to_arrow(hcol, n).to_pylist()

    # device path through jit
    @jax.jit
    def run(b):
        return bound.eval(EvalContext(b, xp=jnp))
    dcol = run(batch)
    dev_vals = device_column_to_arrow(
        jax.tree.map(np.asarray, dcol), n).to_pylist()

    assert _norm(dev_vals) == _norm(host_vals), \
        f"device {dev_vals} != host {host_vals} for {bound.sql()}"
    return host_vals


def _norm(vals):
    out = []
    for v in vals:
        if isinstance(v, float):
            if math.isnan(v):
                out.append("NaN")
            else:
                out.append(round(v, 10))
        else:
            out.append(v)
    return out


LONGS = pa.table({"a": pa.array([1, None, 3, -4, 2**62], type=pa.int64()),
                  "b": pa.array([10, 20, None, 3, 2**62], type=pa.int64())})
DOUBLES = pa.table({
    "x": pa.array([1.5, None, float("nan"), -0.0, 8.0]),
    "y": pa.array([2.0, 1.0, 1.0, 0.0, None])})


def test_add_sub_mul():
    assert eval_both(A.Add(attr("a", T.LONG), attr("b", T.LONG)), LONGS) == \
        [11, None, None, -1, 2**63 - 2**64]  # wraps like Java
    assert eval_both(A.Subtract(attr("a", T.LONG), attr("b", T.LONG)), LONGS)[0] == -9
    assert eval_both(A.Multiply(attr("a", T.LONG), attr("b", T.LONG)), LONGS)[3] == -12


def test_division_family():
    r = eval_both(A.Divide(attr("x", T.DOUBLE), attr("y", T.DOUBLE)), DOUBLES)
    assert r[0] == 0.75 and r[1] is None and r[4] is None
    r = eval_both(A.IntegralDivide(attr("a", T.LONG), attr("b", T.LONG)), LONGS)
    assert r[0] == 0 and r[3] == -1  # trunc toward zero: -4 div 3 = -1
    zeros = pa.table({"a": pa.array([7, -7], type=pa.int64()),
                      "b": pa.array([0, 2], type=pa.int64())})
    assert eval_both(A.IntegralDivide(attr("a", T.LONG), attr("b", T.LONG)),
                     zeros) == [None, -3]
    assert eval_both(A.Remainder(attr("a", T.LONG), attr("b", T.LONG)),
                     zeros) == [None, -1]
    assert eval_both(A.Pmod(attr("a", T.LONG), attr("b", T.LONG)),
                     zeros) == [None, 1]


def test_comparisons_nan_semantics():
    t = pa.table({"x": pa.array([1.0, float("nan"), float("nan"), None]),
                  "y": pa.array([1.0, float("nan"), 1.0, 1.0])})
    assert eval_both(P.EqualTo(attr("x", T.DOUBLE), attr("y", T.DOUBLE)), t) == \
        [True, True, False, None]  # NaN = NaN is TRUE in Spark
    assert eval_both(P.GreaterThan(attr("x", T.DOUBLE), attr("y", T.DOUBLE)), t) == \
        [False, False, True, None]  # NaN > everything
    assert eval_both(P.EqualNullSafe(attr("x", T.DOUBLE), attr("y", T.DOUBLE)), t) == \
        [True, True, False, False]


def test_string_compare():
    t = pa.table({"s": pa.array(["apple", "b", None, "apple"]),
                  "t": pa.array(["apricot", "b", "x", None])})
    assert eval_both(P.LessThan(attr("s", T.STRING), attr("t", T.STRING)), t) == \
        [True, False, None, None]
    assert eval_both(P.EqualTo(attr("s", T.STRING), attr("t", T.STRING)), t) == \
        [False, True, None, None]


def test_three_valued_logic():
    t = pa.table({"p": pa.array([True, False, None, True]),
                  "q": pa.array([None, None, None, False])})
    assert eval_both(P.And(attr("p", T.BOOLEAN), attr("q", T.BOOLEAN)), t) == \
        [None, False, None, False]
    assert eval_both(P.Or(attr("p", T.BOOLEAN), attr("q", T.BOOLEAN)), t) == \
        [True, None, None, True]


def test_in():
    t = pa.table({"a": pa.array([1, 2, None, 5], type=pa.int64())})
    e = P.In(attr("a", T.LONG), (Literal(1, T.LONG), Literal(5, T.LONG)))
    assert eval_both(e, t) == [True, False, None, True]
    e = P.In(attr("a", T.LONG), (Literal(1, T.LONG), Literal(None, T.LONG)))
    assert eval_both(e, t) == [True, None, None, None]


def test_math():
    t = pa.table({"x": pa.array([4.0, 0.0, -1.0, None])})
    assert eval_both(M.Sqrt(attr("x", T.DOUBLE)), t)[0] == 2.0
    logs = eval_both(M.Log(attr("x", T.DOUBLE)), t)
    assert logs[1] is None and logs[2] is None  # Spark: null out of domain
    assert eval_both(M.Ceil(attr("x", T.DOUBLE)), t) == [4, 0, -1, None]


def test_round():
    t = pa.table({"x": pa.array([2.5, 3.5, -2.5, 1.234])})
    r = eval_both(M.Round(attr("x", T.DOUBLE), Literal(0, T.INT)), t)
    assert r == [3.0, 4.0, -3.0, 1.0]  # HALF_UP
    r = eval_both(M.BRound(attr("x", T.DOUBLE), Literal(0, T.INT)), t)
    assert r == [2.0, 4.0, -2.0, 1.0]  # HALF_EVEN


def test_conditional():
    t = pa.table({"p": pa.array([True, False, None]),
                  "a": pa.array([1, 2, 3], type=pa.int64()),
                  "b": pa.array([10, None, 30], type=pa.int64())})
    assert eval_both(Cond.If(attr("p", T.BOOLEAN), attr("a", T.LONG),
                             attr("b", T.LONG)), t) == [1, None, 30]
    assert eval_both(Cond.Coalesce(attr("b", T.LONG), attr("a", T.LONG)), t) == \
        [10, 2, 30]
    cw = Cond.CaseWhen([(P.GreaterThan(attr("a", T.LONG), Literal(2, T.LONG)),
                         Literal(100, T.LONG))], Literal(0, T.LONG))
    assert eval_both(cw, t) == [0, 0, 100]


def test_cast_numeric():
    t = pa.table({"x": pa.array([1.9, -1.9, float("nan"), 1e30])})
    assert eval_both(C.Cast(attr("x", T.DOUBLE), T.INT), t) == \
        [1, -1, 0, 2**31 - 1]  # trunc, NaN->0, saturate
    t2 = pa.table({"a": pa.array([300, -1, None], type=pa.int64())})
    assert eval_both(C.Cast(attr("a", T.LONG), T.BYTE), t2) == \
        [44, -1, None]  # wraps
    assert eval_both(C.Cast(attr("a", T.LONG), T.DOUBLE), t2) == \
        [300.0, -1.0, None]


def test_cast_decimal():
    import decimal
    t = pa.table({"d": pa.array([decimal.Decimal("12.345"), None,
                                 decimal.Decimal("-0.005")],
                                type=pa.decimal128(10, 3))})
    dt = T.DecimalType(10, 3)
    e = C.Cast(AttributeReference("d", dt), T.DecimalType(10, 2))
    assert eval_both(e, t) == [decimal.Decimal("12.35"), None,
                               decimal.Decimal("-0.01")]  # HALF_UP away from 0
    e = C.Cast(AttributeReference("d", dt), T.LONG)
    assert eval_both(e, t) == [12, None, 0]


# --------------------------------------------------------------------------
# Spark-exact murmur3: compare against an independent scalar implementation
# of the published algorithm
# --------------------------------------------------------------------------

def _py_mixk1(k1):
    k1 = (k1 * 0xcc9e2d51) & 0xFFFFFFFF
    k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
    return (k1 * 0x1b873593) & 0xFFFFFFFF


def _py_mixh1(h1, k1):
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
    return (h1 * 5 + 0xe6546b64) & 0xFFFFFFFF


def _py_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85ebca6b) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xc2b2ae35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


def _signed32(v):
    return v - 2**32 if v >= 2**31 else v


def _py_hash_int(v, seed=42):
    return _signed32(_py_fmix(_py_mixh1(seed, _py_mixk1(v & 0xFFFFFFFF)), 4))


def _py_hash_long(v, seed=42):
    low = v & 0xFFFFFFFF
    high = (v >> 32) & 0xFFFFFFFF
    h1 = _py_mixh1(seed, _py_mixk1(low))
    h1 = _py_mixh1(h1, _py_mixk1(high))
    return _signed32(_py_fmix(h1, 8))


def _py_hash_bytes(bs, seed=42):
    h1 = seed
    n = len(bs) // 4
    for i in range(n):
        block = int.from_bytes(bs[4 * i:4 * i + 4], "little")
        h1 = _py_mixh1(h1, _py_mixk1(block))
    for b in bs[4 * n:]:
        sb = b - 256 if b >= 128 else b
        h1 = _py_mixh1(h1, _py_mixk1(sb & 0xFFFFFFFF))
    return _signed32(_py_fmix(h1, len(bs)))


def test_murmur3_parity():
    ints = [0, 1, -1, 42, 2**31 - 1, -(2**31)]
    t = pa.table({"i": pa.array(ints, type=pa.int32())})
    got = eval_both(H.Murmur3Hash(attr("i", T.INT)), t)
    assert got == [_py_hash_int(v) for v in ints]

    longs = [0, 1, -1, 2**63 - 1, -(2**63), 123456789012345]
    t = pa.table({"l": pa.array(longs, type=pa.int64())})
    got = eval_both(H.Murmur3Hash(attr("l", T.LONG)), t)
    assert got == [_py_hash_long(v) for v in longs]

    strs = ["", "a", "ab", "abc", "abcd", "abcde", "hello world — ünïcødé"]
    t = pa.table({"s": pa.array(strs)})
    got = eval_both(H.Murmur3Hash(attr("s", T.STRING)), t)
    assert got == [_py_hash_bytes(s.encode()) for s in strs]


def test_murmur3_multi_column_null_skip():
    t = pa.table({"i": pa.array([1, None], type=pa.int32()),
                  "l": pa.array([None, 2], type=pa.int64())})
    got = eval_both(H.Murmur3Hash(attr("i", T.INT), attr("l", T.LONG)), t)
    # null column leaves hash unchanged: row0 = hash_int(1); row1 uses seed
    # then hash_long(2)
    assert got[0] == _py_hash_int(1)
    assert got[1] == _py_hash_long(2)


def test_xxhash64_long_known():
    # standard XXH64 of an 8-byte little-endian int with seed 42 — verified
    # values computed with the scalar algorithm below
    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & (2**64 - 1)

    P1, P2, P3, P4, P5 = (0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F,
                          0x165667B19E3779F9, 0x85EBCA77C2B2AE63,
                          0x27D4EB2F165667C5)

    def xxh64_long(v, seed=42):
        h = (seed + P5 + 8) & (2**64 - 1)
        k = v & (2**64 - 1)
        k = (k * P2) & (2**64 - 1)
        k = rotl(k, 31)
        k = (k * P1) & (2**64 - 1)
        h ^= k
        h = rotl(h, 27)
        h = (h * P1 + P4) & (2**64 - 1)
        h ^= h >> 33
        h = (h * P2) & (2**64 - 1)
        h ^= h >> 29
        h = (h * P3) & (2**64 - 1)
        h ^= h >> 32
        return h - 2**64 if h >= 2**63 else h

    longs = [0, 1, -1, 42, 2**63 - 1]
    t = pa.table({"l": pa.array(longs, type=pa.int64())})
    got = eval_both(H.XxHash64(attr("l", T.LONG)), t)
    assert got == [xxh64_long(v) for v in longs]


from spark_rapids_tpu.sql import functions as F  # noqa: E402


# --- task-context leaf expressions -----------------------------------------

def test_spark_partition_id_and_mono_id(session):
    df = session.create_dataframe(pa.table({"x": list(range(20))}),
                               num_partitions=4)
    out = df.select(df.x, F.spark_partition_id().alias("p"),
                    F.monotonically_increasing_id().alias("m")).collect()
    assert set(out["p"].to_pylist()) == {0, 1, 2, 3}
    ms = out["m"].to_pylist()
    assert len(set(ms)) == 20
    # id layout: partition in the high bits
    for p, m in zip(out["p"].to_pylist(), ms):
        assert m >> 33 == p


def test_rand_deterministic_per_seed(session):
    df = session.create_dataframe(pa.table({"x": list(range(100))}),
                               num_partitions=2)
    a = df.select(F.rand(7).alias("r")).collect()["r"].to_pylist()
    b = df.select(F.rand(7).alias("r")).collect()["r"].to_pylist()
    assert a == b  # same seed -> same stream
    c = df.select(F.rand(8).alias("r")).collect()["r"].to_pylist()
    assert a != c
    assert all(0.0 <= v < 1.0 for v in a)


def test_unscaled_value_and_make_decimal(session):
    import decimal as D
    from spark_rapids_tpu.sql.expressions.arithmetic import (MakeDecimal,
                                                             UnscaledValue)
    from spark_rapids_tpu.sql.dataframe import Column
    t = pa.table({"d": pa.array([D.Decimal("12.34"), D.Decimal("-0.01"),
                                 None], type=pa.decimal128(9, 2))})
    df = session.create_dataframe(t)
    out = df.select(Column(UnscaledValue(df.d.expr)).alias("u")).collect()
    assert out["u"].to_pylist() == [1234, -1, None]
    df2 = session.create_dataframe(pa.table({"l": pa.array([1234, -1, None],
                                                        type=pa.int64())}))
    back = df2.select(Column(MakeDecimal(df2.l.expr, 9, 2)).alias("d")) \
        .collect()
    assert back["d"].to_pylist() == [D.Decimal("12.34"),
                                     D.Decimal("-0.01"), None]


# --- DISTINCT aggregates (dedup-then-aggregate rewrite) --------------------

def test_count_distinct_on_device(session):
    df = session.create_dataframe(pa.table({
        "k": [1, 1, 1, 2, 2, 2],
        "v": pa.array([5, 5, None, 9, 9, 8], type=pa.int64())}),
        num_partitions=3)
    q = df.groupBy("k").agg(F.countDistinct(F.col("v")).alias("c"))
    ex = session.explain(q)
    assert "host" not in ex, ex
    out = q.orderBy("k").collect().to_pylist()
    # count(DISTINCT v) ignores nulls (Spark)
    assert out == [{"k": 1, "c": 1}, {"k": 2, "c": 2}]


def test_sum_distinct_and_strings(session):
    df = session.create_dataframe(pa.table({
        "k": ["a", "a", "b", "b", "b"],
        "v": [2.0, 2.0, 3.0, 3.0, 4.0]}), num_partitions=2)
    out = (df.groupBy("k").agg(F.sumDistinct(F.col("v")).alias("s"))
           .orderBy("k").collect().to_pylist())
    assert out == [{"k": "a", "s": 2.0}, {"k": "b", "s": 7.0}]


def test_count_distinct_single_column(session):
    df = session.create_dataframe(pa.table({
        "k": [1, 1, 1, 1], "a": [1, 1, 2, 2]}))
    out = (df.groupBy("k").agg(F.countDistinct(F.col("a")).alias("c"))
           .collect().to_pylist())
    assert out == [{"k": 1, "c": 2}]


def test_mixed_distinct_basic(session):
    """Mixed DISTINCT + plain aggregates: the duplicate-heavy two-row
    case that the old silent host fallback used to get wrong (c=2)."""
    df = session.create_dataframe(pa.table({"k": [1, 1], "v": [5.0, 5.0]}))
    q = df.groupBy("k").agg(F.countDistinct(F.col("v")).alias("c"),
                            F.sum(F.col("v")).alias("s"))
    assert q.collect().to_pylist() == [{"k": 1, "c": 1, "s": 10.0}]


def test_distinct_device_vs_host_oracle(session):
    rng = np.random.default_rng(21)
    t = pa.table({"g": rng.integers(0, 10, 5000),
                  "v": rng.integers(0, 30, 5000)})
    q = lambda s: (s.create_dataframe(t, num_partitions=4).groupBy("g")
                   .agg(F.countDistinct(F.col("v")).alias("c"))
                   .orderBy("g").collect().to_pylist())
    import spark_rapids_tpu as srt
    try:
        dev = q(srt.session())
        host = q(srt.session(**{"spark.rapids.sql.enabled": False}))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
    assert dev == host
    pdf = t.to_pandas()
    want = pdf.groupby("g")["v"].nunique()
    for r in dev:
        assert r["c"] == want[r["g"]]


def test_multi_column_count_distinct_on_device(session):
    df = session.create_dataframe(pa.table({
        "k": [1, 1, 1, 1, 1],
        "a": pa.array([1, 1, 2, 2, None], type=pa.int64()),
        "b": pa.array([1, 1, 1, 2, 3], type=pa.int64())}),
        num_partitions=2)
    q = df.groupBy("k").agg(
        F.countDistinct(F.col("a"), F.col("b")).alias("c"))
    assert "host" not in session.explain(q)
    # distinct non-null tuples: (1,1), (2,1), (2,2); (None,3) excluded
    assert q.collect().to_pylist() == [{"k": 1, "c": 3}]


def test_mixed_distinct_with_plain_aggs(session):
    """Mixed DISTINCT + plain aggregates: inner partial agg over
    (keys, distinct values), outer merge of partial slots + plain agg of
    deduped values (PreMergedAggregate layering)."""
    import numpy as np
    rng = np.random.default_rng(17)
    n = 8000
    t = pa.table({"k": rng.integers(0, 30, n),
                  "v": rng.integers(0, 15, n),
                  "w": rng.random(n)})
    df = session.create_dataframe(t, num_partitions=3)
    q = (df.groupBy("k").agg(F.countDistinct(F.col("v")).alias("cd"),
                             F.sum(F.col("w")).alias("sw"),
                             F.min(F.col("v")).alias("mv"),
                             F.avg(F.col("w")).alias("aw"),
                             F.count("*").alias("c"))
         .orderBy("k"))
    assert "host" not in session.explain(q)
    got = q.collect().to_pandas().set_index("k")
    pdf = t.to_pandas()
    exp = pdf.groupby("k").agg(cd=("v", "nunique"), sw=("w", "sum"),
                               mv=("v", "min"), aw=("w", "mean"),
                               c=("w", "size"))
    assert (got.index == exp.index).all()
    assert (got["cd"].values == exp["cd"].values).all()
    assert np.allclose(got["sw"], exp["sw"])
    assert (got["mv"].values == exp["mv"].values).all()
    assert np.allclose(got["aw"], exp["aw"])
    assert (got["c"].values == exp["c"].values).all()


def test_mixed_distinct_stddev_and_strings(session):
    import numpy as np
    rng = np.random.default_rng(18)
    n = 3000
    t = pa.table({"k": rng.integers(0, 10, n),
                  "v": rng.integers(0, 8, n),
                  "s": [f"x{i % 7}" for i in range(n)],
                  "w": rng.random(n)})
    df = session.create_dataframe(t, num_partitions=2)
    q = (df.groupBy("k").agg(F.countDistinct(F.col("v")).alias("cd"),
                             F.stddev(F.col("w")).alias("sd"),
                             F.max(F.col("s")).alias("mx"))
         .orderBy("k"))
    got = q.collect().to_pandas().set_index("k")
    pdf = t.to_pandas()
    exp = pdf.groupby("k").agg(cd=("v", "nunique"), sd=("w", "std"),
                               mx=("s", "max"))
    assert (got["cd"].values == exp["cd"].values).all()
    assert np.allclose(got["sd"], exp["sd"], rtol=1e-9)
    assert (got["mx"].values == exp["mx"].values).all()


def test_mixed_distinct_with_collect_still_raises(session):
    df = session.create_dataframe(pa.table({"k": [1], "v": [1.0]}))
    q = df.groupBy("k").agg(F.countDistinct(F.col("v")).alias("c"),
                            F.collect_list(F.col("v")).alias("l"))
    with pytest.raises(NotImplementedError, match="DISTINCT"):
        q.collect()
