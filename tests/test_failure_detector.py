"""Pod-scale fault domain tests — the phi-accrual failure detector's
state machine (hysteresis, sticky dead, revive), epoch fencing at the
transport SPI seam (reference strategy: unit-test distributed logic with
a mock transport, no cluster), speculative duplicate fetches, the
blacklist reinstatement-race regression, spill disk-full handling, and
the mesh collective watchdog.  The real N-process scenarios live in
``testing/chaos_cluster.py`` (slow-marked smoke here; CI runs the full
harness)."""

import errno
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.convert import arrow_to_device
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.robustness import arm_chaos, disarm_chaos
from spark_rapids_tpu.robustness import failure_detector as fd
from spark_rapids_tpu.shuffle import (FETCH_STATS, LocalTransport,
                                      PeerBlacklist,
                                      ShuffleHeartbeatManager,
                                      ShuffleManager)
from spark_rapids_tpu.shuffle.transport import (BlockId, PeerInfo,
                                                StaleBlockEpoch)


def small_table(n=24, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 8, n), "v": rng.random(n)})


# ---------------------------------------------------------------------------
# detector state machine (time-controlled: every API takes an explicit now)
# ---------------------------------------------------------------------------

def _beats(det, eid, start, n, dt):
    t = start
    for _ in range(n):
        det.observe(eid, now=t)
        t += dt
    return t - dt   # time of the last beat


def test_detector_alive_suspect_dead():
    det = fd.FailureDetector(suspect_ms=300, dead_ms=800)
    last = _beats(det, "p", 0.0, 6, 0.1)
    assert det.state("p") == fd.ALIVE
    det.sweep(now=last + 0.29)
    assert det.state("p") == fd.ALIVE       # inside the grace window
    det.sweep(now=last + 0.35)
    assert det.state("p") == fd.SUSPECT     # silent past suspectMs
    det.sweep(now=last + 0.9)
    assert det.state("p") == fd.DEAD        # silent past the hard bound
    assert det.is_dead("p")


def test_detector_suspect_heals_with_hysteresis():
    # jitter_scale=0 pins the on-time threshold at suspectMs, so the
    # late beat below is unambiguously off-time
    det = fd.FailureDetector(suspect_ms=300, dead_ms=800, recover_beats=2,
                             jitter_scale=0.0)
    last = _beats(det, "p", 0.0, 6, 0.1)
    det.sweep(now=last + 0.4)
    assert det.state("p") == fd.SUSPECT
    # the late re-arrival beat is off-time: no credit
    det.observe("p", now=last + 0.4)
    assert det.state("p") == fd.SUSPECT
    # one on-time beat is NOT enough (hysteresis) ...
    det.observe("p", now=last + 0.5)
    assert det.state("p") == fd.SUSPECT
    # ... two consecutive on-time beats heal it
    det.observe("p", now=last + 0.6)
    assert det.state("p") == fd.ALIVE
    assert fd.STATS["recovered"] >= 1


def test_detector_dead_is_sticky_until_revive():
    det = fd.FailureDetector(suspect_ms=100, dead_ms=200)
    before = fd.STATS["revived"]
    det.observe("p", now=0.0)
    det.observe("p", now=0.1)
    det.sweep(now=1.0)
    assert det.is_dead("p")
    # heartbeats from a zombie must NOT resurrect it
    det.observe("p", now=1.1)
    det.observe("p", now=1.2)
    assert det.is_dead("p")
    # only the re-registration path (epoch bump first) revives
    det.revive("p", now=1.3)
    assert det.state("p") == fd.ALIVE
    assert fd.STATS["revived"] == before + 1


def test_detector_transition_callbacks_and_death_generation():
    det = fd.FailureDetector(suspect_ms=100, dead_ms=200)
    seen = []
    det.on_transition(lambda e, old, new: seen.append((e, old, new)))
    gen0 = det.death_generation
    det.observe("p", now=0.0)
    det.observe("p", now=0.1)
    det.sweep(now=5.0)
    assert ("p", fd.SUSPECT, fd.DEAD) in seen or \
        ("p", fd.ALIVE, fd.DEAD) in seen
    assert det.death_generation == gen0 + 1


def test_detector_phi_grows_with_silence():
    det = fd.FailureDetector()
    last = _beats(det, "p", 0.0, 8, 0.1)
    early = det.phi("p", now=last + 0.05)
    late = det.phi("p", now=last + 2.0)
    assert late > early >= 0.0


def test_detector_jitter_scales_suspect_grace():
    """Phi-accrual: a peer whose heartbeats normally wobble gets
    proportionally more grace before SUSPECT; a steady peer does not."""
    det = fd.FailureDetector(suspect_ms=300, dead_ms=5_000,
                             jitter_scale=4.0)
    t = 0.0
    for i in range(10):                      # jittery: dt alternates
        det.observe("wobbly", now=t)
        t += 0.1 if i % 2 == 0 else 0.3
    wob_last = t - (0.3 if (10 - 1) % 2 == 1 else 0.1)
    steady_last = _beats(det, "steady", 0.0, 10, 0.1)
    det.sweep(now=max(wob_last, steady_last) + 0.45)
    assert det.state("steady") == fd.SUSPECT
    assert det.state("wobbly") == fd.ALIVE


def test_chaos_peer_kill_and_stall_sites():
    try:
        arm_chaos(seed=3, sites="peer.kill:1.0")
        det = fd.FailureDetector()
        det.observe("p")       # the drawn kill force-declares dead
        det.observe("p")
        assert det.is_dead("p")
        disarm_chaos()
        arm_chaos(seed=3, sites="peer.stall:1.0")
        det2 = fd.FailureDetector(suspect_ms=100, dead_ms=10_000)
        det2.observe("q", now=0.0)
        det2.observe("q", now=0.1)   # dropped observation: q looks stalled
        assert det2.state("q") == fd.ALIVE
    finally:
        disarm_chaos()


def test_heartbeat_loop_close_joins_thread():
    hits = []
    loop = fd.HeartbeatLoop(lambda: hits.append(1), 0.01, name="t")
    time.sleep(0.08)
    loop.close()
    assert hits                      # it beat at least once
    assert not any(t.name.startswith(fd.THREAD_PREFIX)
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# epoch fencing at the SPI seam
# ---------------------------------------------------------------------------

def _ici_conf(**extra):
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    for k, v in extra.items():
        conf.set(k.replace("__", "."), v)
    return conf


def test_epoch_fencing_refuses_stale_blocks():
    """A zombie (old process serving after its executor id re-registered
    under a bumped epoch) must have every response refused as LOST and
    recovered via lineage — bit-identically."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    conf.set("spark.rapids.tpu.peers.heartbeatMs", 60_000)  # armed
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    b = ShuffleManager(conf, transport, "exec-B", hb)
    try:
        t = small_table(30)
        b.write_map_output(11, 0, [arrow_to_device(t)])
        a._beat()                     # learn B + its epoch (1)
        assert a._peer_epochs.get("exec-B") == 1
        # epoch-matched serving passes the fence
        transport.serving_epochs["exec-B"] = 1
        got = a.read_reduce_partition(11, 1, 0)
        assert got is not None and got.num_rows_int == 30

        # B's executor id re-registers (epoch bump) but the OLD process
        # still serves at epoch 1: every fetch must refuse it
        hb.expire_now("exec-B")
        hb.register("exec-B", "local")
        assert hb.epoch_of("exec-B") == 2
        a._beat()
        assert a._peer_epochs["exec-B"] == 2
        stale0 = FETCH_STATS["stale_epoch"]
        rec0 = FETCH_STATS["recomputed"]
        # a fresh shuffle from B forces the remote path: the zombie's
        # response is stamped epoch 1 < expected 2 -> refused as LOST
        # and recovered via lineage, bit-identically
        b.write_map_output(13, 0, [arrow_to_device(t)])
        a.register_recompute(
            13, lambda mid: a.write_map_output(
                13, mid, [arrow_to_device(t)]))
        got3 = a.read_reduce_partition(13, 1, 0)
        assert got3 is not None and got3.num_rows_int == 30
        assert FETCH_STATS["stale_epoch"] > stale0
        assert FETCH_STATS["recomputed"] > rec0
    finally:
        a.close()
        b.close()


def test_fencing_degrades_off_for_epochless_transports():
    """A transport that cannot stamp epochs (served=None — old peers,
    the plain-op wire path) must never be refused."""
    conf = _ici_conf()
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    b = ShuffleManager(conf, transport, "exec-B", hb)
    try:
        b.write_map_output(17, 0, [arrow_to_device(small_table(12))])
        # no serving_epochs entry: fetch_with_epoch reports None
        got = a.read_reduce_partition(17, 1, 0)
        assert got is not None and got.num_rows_int == 12
    finally:
        a.close()
        b.close()


def test_registry_epochs_survive_expiry():
    # authoritative eviction path (dead declaration)
    hb = ShuffleHeartbeatManager()
    hb.register("e1", "ep1")
    assert hb.epoch_of("e1") == 1
    hb.register("e1", "ep1")       # re-register while PRESENT: no bump
    assert hb.epoch_of("e1") == 1
    hb.expire_now("e1")
    hb.register("e1", "ep1")       # re-registration AFTER eviction
    assert hb.epoch_of("e1") == 2  # the fencing token moved

    # heartbeat-timeout expiry path bumps the same token
    hb2 = ShuffleHeartbeatManager(heartbeat_timeout_s=0.0)
    hb2.register("e1", "ep1")
    time.sleep(0.002)
    hb2.heartbeat("e2")            # prunes e1 (silent past timeout 0)
    assert "e1" not in hb2.executors()
    hb2.register("e1", "ep1")
    assert hb2.epoch_of("e1") == 2


# ---------------------------------------------------------------------------
# blacklist reinstatement race (generation fencing)
# ---------------------------------------------------------------------------

def test_blacklist_generation_drops_stale_reports():
    bl = PeerBlacklist(threshold=1, ttl_s=0.02)
    gen = bl.generation("p")
    assert bl.record_failure("p", gen) is True   # benched
    time.sleep(0.03)
    assert bl.reinstate_expired() == ["p"]       # generation bumps
    # the stale report from before the bench/reinstate cycle must not
    # re-bench the peer
    assert bl.record_failure("p", gen) is False
    assert not bl.is_blacklisted("p")
    # a fresh-generation report counts again
    assert bl.record_failure("p", bl.generation("p")) is True


def test_blacklist_generation_race_with_paused_fetch_thread():
    """Regression: a fetch thread snapshots the generation, stalls
    mid-fetch while the peer is benched AND reinstated, then reports its
    (stale) failure — the report must be dropped, not re-bench the
    freshly reinstated peer."""
    bl = PeerBlacklist(threshold=1, ttl_s=0.02)
    snapped = threading.Event()
    resume = threading.Event()
    verdict = []

    def paused_fetcher():
        gen = bl.generation("exec-R")
        snapped.set()
        resume.wait(5.0)             # ... fetch in flight, very slowly
        verdict.append(bl.record_failure("exec-R", gen))

    th = threading.Thread(target=paused_fetcher)
    th.start()
    assert snapped.wait(5.0)
    # meanwhile: the peer fails for someone else, gets benched, the
    # bench expires, and a heartbeat refresh reinstates it
    assert bl.record_failure("exec-R", bl.generation("exec-R")) is True
    time.sleep(0.03)
    assert bl.reinstate_expired() == ["exec-R"]
    resume.set()
    th.join(5.0)
    assert verdict == [False]
    assert not bl.is_blacklisted("exec-R")


def test_blacklist_success_bumps_generation():
    bl = PeerBlacklist(threshold=1, ttl_s=60.0)
    gen = bl.generation("p")
    assert bl.record_failure("p", gen) is True
    bl.record_success("p")           # un-benched by a served fetch
    assert not bl.is_blacklisted("p")
    assert bl.record_failure("p", gen) is False   # stale report dropped


# ---------------------------------------------------------------------------
# speculative duplicate fetch
# ---------------------------------------------------------------------------

def test_speculative_fetch_backup_wins():
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    conf.set("spark.rapids.tpu.shuffle.fetch.speculativeP99Factor", 2.0)
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    slow = ShuffleManager(conf, transport, "exec-SLOW", hb)
    fast = ShuffleManager(conf, transport, "exec-FAST", hb)
    try:
        batch = arrow_to_device(small_table(16))
        fast.write_map_output(31, 0, [batch])

        def hook(peer, block):
            if peer.executor_id == "exec-SLOW":
                time.sleep(0.25)     # straggler
            return None              # fall through to the real store

        transport.fetch_hook = hook
        # warm the latency window so the p99 budget is tiny
        with a._lock:
            a._fetch_latencies.extend([0.005] * 16)
        sp0, wins0 = (FETCH_STATS["speculated"],
                      FETCH_STATS["speculative_wins"])
        got = a.read_reduce_partition(31, 1, 0)
        assert got is not None and got.num_rows_int == 16
        assert FETCH_STATS["speculated"] > sp0
        assert FETCH_STATS["speculative_wins"] > wins0
    finally:
        a.close()
        slow.close()
        fast.close()


def test_speculation_off_by_default():
    conf = _ici_conf()
    hb = ShuffleHeartbeatManager()
    a = ShuffleManager(conf, LocalTransport(), "exec-A", hb)
    try:
        assert a._speculative_factor == 0.0
        assert a._fetch_p99() is None
        assert a._spec_pool is None
    finally:
        a.close()


# ---------------------------------------------------------------------------
# detector-armed manager wiring
# ---------------------------------------------------------------------------

def test_manager_detector_disarmed_by_default():
    conf = _ici_conf()
    a = ShuffleManager(conf, LocalTransport(), "exec-A",
                       ShuffleHeartbeatManager())
    try:
        assert a.detector_armed is False
        assert a._hb_loop is None
    finally:
        a.close()


def test_manager_close_drains_fault_domain_state():
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    conf.set("spark.rapids.tpu.peers.heartbeatMs", 20)
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    b = ShuffleManager(conf, transport, "exec-B", hb)
    try:
        assert a.detector_armed and a._hb_loop is not None
        time.sleep(0.08)             # a few beats observe the peers
        assert a.detector.peer_count() >= 1
    finally:
        a.close()
        b.close()
    assert a.detector.peer_count() == 0
    assert a._peer_epochs == {} and a._block_sources == {}
    assert not any(t.name.startswith(fd.THREAD_PREFIX)
                   for t in threading.enumerate())


def test_healthz_exposes_peer_liveness():
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    conf.set("spark.rapids.tpu.peers.heartbeatMs", 60_000)
    a = ShuffleManager(conf, LocalTransport(), "exec-A",
                       ShuffleHeartbeatManager())
    try:
        live = a.peer_liveness()
        assert live["armed"] is True
        assert set(live) >= {"alive", "suspect", "dead", "epoch",
                             "peer_epochs", "phi"}
        assert a.epoch == 1          # registry-assigned serving epoch
    finally:
        a.close()


# ---------------------------------------------------------------------------
# spill disk-full (satellite)
# ---------------------------------------------------------------------------

def test_spill_enospc_is_non_retriable():
    from spark_rapids_tpu.memory import spill as sp
    calls = []

    def fails_enospc():
        calls.append(1)
        raise OSError(errno.ENOSPC, "No space left on device")

    with pytest.raises(sp.SpillDiskFull):
        sp._retry_disk_io(fails_enospc, "test-write")
    assert len(calls) == 1           # no retry budget burned


def test_spill_transient_oserror_still_retries():
    from spark_rapids_tpu.memory import spill as sp
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise OSError(errno.EIO, "transient")
        return "ok"

    assert sp._retry_disk_io(flaky, "test-write") == "ok"
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# mesh collective watchdog (satellite)
# ---------------------------------------------------------------------------

def test_mesh_collective_deadline_watchdog():
    from spark_rapids_tpu.parallel import mesh as M
    # inline fast path: no deadline, runs on the calling thread
    assert M._run_with_deadline(lambda: 42, 0.0) == 42
    # a collective overrunning its deadline degrades loudly
    t0 = M.STATS["collective_timeouts"]
    with pytest.raises(M.MeshCollectiveTimeout):
        M._run_with_deadline(lambda: time.sleep(0.5) or 1, 0.05)
    assert M.STATS["collective_timeouts"] == t0 + 1
    # errors inside the deadline marshal back to the caller
    def boom():
        raise ValueError("inner")
    with pytest.raises(ValueError):
        M._run_with_deadline(boom, 5.0)


def test_mesh_collective_timeout_degrades_to_fallback():
    """MeshCollectiveTimeout subclasses MeshShuffleUnsupported ON
    PURPOSE: the exchange exec's existing fallback catch must degrade
    the stage to the local plane instead of failing the query."""
    from spark_rapids_tpu.parallel import mesh as M
    assert issubclass(M.MeshCollectiveTimeout, M.MeshShuffleUnsupported)


def test_mesh_collective_timeout_chaos_site():
    from spark_rapids_tpu.parallel import mesh as M
    try:
        arm_chaos(seed=5, sites="mesh.collective.timeout:1.0")
        with pytest.raises(M.MeshCollectiveTimeout):
            M.mesh_shuffle_batches(None, [], [], 0)
    finally:
        disarm_chaos()


# ---------------------------------------------------------------------------
# observability folding
# ---------------------------------------------------------------------------

def test_stats_snapshot_includes_fault_domain_counters():
    from spark_rapids_tpu.robustness import stats_snapshot
    snap = stats_snapshot()
    for key in ("staleEpochsRefused", "deadPeerFailovers",
                "proactiveRecomputes", "speculativeFetches",
                "speculativeFetchWins", "peersSuspected",
                "peersDeclaredDead", "peersRecovered", "peersRevived"):
        assert key in snap, key


# ---------------------------------------------------------------------------
# the real N-process harness (slow: CI runs the full scenario suite)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_cluster_sigkill_smoke():
    from spark_rapids_tpu.testing.chaos_cluster import run_sigkill
    r = run_sigkill(nprocs=3, seed=11, rows=256)
    assert r["ok"] and r["blocks_recomputed"] > 0


@pytest.mark.slow
def test_chaos_cluster_zombie_fencing():
    from spark_rapids_tpu.testing.chaos_cluster import run_zombie
    r = run_zombie(nprocs=3, seed=11, rows=256)
    assert r["ok"] and r["stale_epochs_refused"] > 0
