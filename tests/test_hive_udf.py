"""Hive UDF bridge (hiveUDFs.scala / rowBasedHiveUDFs.scala analog):
CREATE TEMPORARY FUNCTION ... AS 'module.Class', row-based host
execution, and the device-columnar SPI path."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F


class TitleCase:
    """Row-based Hive-style UDF (GenericUDF analog)."""

    return_type = T.STRING

    def evaluate(self, s):
        return s.title() if s is not None else None


class PlusN:
    return_type = T.LONG

    def __init__(self, n: int = 10):
        self.n = n

    def evaluate(self, v):
        return None if v is None else v + self.n


class DoubleIt:
    """Device-columnar Hive UDF (RapidsUDF SPI analog): runs inside the
    jitted kernel on DeviceColumns."""

    return_type = T.DOUBLE

    def evaluate_columnar(self, ctx, col):
        from spark_rapids_tpu.columnar import DeviceColumn
        return DeviceColumn(T.DOUBLE, col.data * 2.0, col.validity)


@pytest.fixture()
def sess():
    return srt.session()


def _df(sess):
    t = pa.table({"s": ["hello world", None, "a b"],
                  "v": pa.array([1, 2, None], pa.int64()),
                  "x": [1.5, 2.5, 3.5]})
    df = sess.create_dataframe(t)
    df.createOrReplaceTempView("hv")
    return df


def test_create_temporary_function_sql(sess):
    _df(sess)
    sess.sql("CREATE TEMPORARY FUNCTION title_case AS "
             "'test_hive_udf.TitleCase'")
    out = sess.sql("SELECT title_case(s) AS t FROM hv").collect().to_pylist()
    assert [r["t"] for r in out] == ["Hello World", None, "A B"]


def test_row_based_runs_on_host(sess):
    df = _df(sess)
    sess.register_hive_function("plus_n", PlusN(100))
    q = sess.sql("SELECT plus_n(v) AS p FROM hv")
    assert "host" in sess.explain(q)
    assert [r["p"] for r in q.collect().to_pylist()] == [101, 102, None]


def test_columnar_spi_runs_on_device(sess):
    _df(sess)
    sess.register_hive_function("double_it", DoubleIt)
    q = sess.sql("SELECT double_it(x) AS d FROM hv")
    assert "cannot run" not in sess.explain(q)
    assert [r["d"] for r in q.collect().to_pylist()] == [3.0, 5.0, 7.0]


def test_create_or_replace_and_drop(sess):
    _df(sess)
    sess.sql("CREATE TEMPORARY FUNCTION f1 AS 'test_hive_udf.TitleCase'")
    with pytest.raises(ValueError, match="already exists"):
        sess.sql("CREATE TEMPORARY FUNCTION f1 AS 'test_hive_udf.PlusN'")
    sess.sql("CREATE OR REPLACE TEMPORARY FUNCTION f1 AS "
             "'test_hive_udf.PlusN'")
    out = sess.sql("SELECT f1(v) AS p FROM hv").collect().to_pylist()
    assert out[0]["p"] == 11  # PlusN default n=10
    sess.sql("DROP TEMPORARY FUNCTION f1")
    with pytest.raises(Exception):
        sess.sql("SELECT f1(v) FROM hv")
    sess.sql("DROP TEMPORARY FUNCTION IF EXISTS f1")  # no error


def test_bad_class_path(sess):
    with pytest.raises(ValueError, match="cannot load"):
        sess.sql("CREATE TEMPORARY FUNCTION bad AS 'no.such.Cls'")


def test_missing_return_type_rejected(sess):
    class NoRT:
        def evaluate(self, x):
            return x
    with pytest.raises(ValueError, match="return_type"):
        sess.register_hive_function("nort", NoRT())


def test_udf_composes_with_engine_exprs(sess):
    _df(sess)
    sess.register_hive_function("double_it", DoubleIt)
    out = sess.sql(
        "SELECT sum(double_it(x)) AS s FROM hv WHERE v IS NOT NULL"
    ).collect().to_pylist()
    assert out[0]["s"] == pytest.approx((1.5 + 2.5) * 2)


def test_udf_visible_in_selectExpr_and_filter(sess):
    """Temporary functions must resolve on ALL expression-string
    surfaces, not just session.sql (Spark parity)."""
    df = _df(sess)
    sess.register_hive_function("plus_n", PlusN(1))
    out = df.selectExpr("plus_n(v) AS p").collect().to_pylist()
    assert [r["p"] for r in out] == [2, 3, None]
    got = df.filter("plus_n(v) > 2").select(df.v).collect().to_pylist()
    assert [r["v"] for r in got] == [2]
