"""Iceberg-analog table format (reference GPU Iceberg read path,
``sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/``): snapshot
reads, time travel, partition-transform + column-bound pruning, field-id
schema evolution, position deletes, avro manifests."""

import datetime
import os
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.iceberg import IcebergTable, parse_transform
from spark_rapids_tpu.iceberg.metadata import (latest_metadata_version,
                                               read_table_metadata)


@pytest.fixture()
def sess():
    return srt.session()


SCHEMA = T.StructType([
    T.StructField("id", T.LONG, False),
    T.StructField("v", T.DOUBLE, True),
    T.StructField("tag", T.STRING, True),
])


def make_batch(lo, hi, tag="a"):
    n = hi - lo
    return pa.table({
        "id": pa.array(range(lo, hi), type=pa.int64()),
        "v": pa.array([float(i) * 0.5 for i in range(lo, hi)]),
        "tag": [tag] * n,
    })


def test_create_append_read(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 50))
    t.append(make_batch(50, 100))
    df = t.to_df().orderBy("id").collect()
    assert df["id"].to_pylist() == list(range(100))
    # metadata versions: create + 2 appends
    assert latest_metadata_version(str(tmp_path / "t")) == 2


def test_snapshot_time_travel(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 10))
    first = t.meta.current_snapshot_id
    t.append(make_batch(10, 20))
    cur = t.to_df().collect()
    old = t.to_df(snapshot_id=first).collect()
    assert cur.num_rows == 20
    assert old.num_rows == 10
    hist = t.history()
    assert [h["operation"] for h in hist] == ["append", "append"]
    # timestamp travel: as-of the first snapshot's commit time
    ts0 = t.meta.snapshots[0].timestamp_ms
    asof = t.to_df(as_of_timestamp_ms=ts0).collect()
    assert asof.num_rows == 10


def test_reader_format_integration(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 30))
    df = sess.read.format("iceberg").load(str(tmp_path / "t"))
    assert df.count() == 30
    first = t.meta.current_snapshot_id
    t.append(make_batch(30, 60))
    df_old = (sess.read.format("iceberg").option("snapshot-id", first)
              .load(str(tmp_path / "t")))
    assert df_old.count() == 30


def test_partition_pruning_identity(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA,
                            partition_by=[("tag", "identity")])
    t.append(make_batch(0, 10, "a"))
    t.append(make_batch(10, 20, "b"))
    t.append(make_batch(20, 30, "c"))
    assert len(t.planned_files()) == 3
    pruned = t.planned_files([("tag", "=", "b")])
    assert len(pruned) == 1
    rows = t.to_df(filters=[("tag", "=", "b")]).collect()
    assert sorted(rows["id"].to_pylist()) == list(range(10, 20))
    # != prunes the matching identity partition
    assert len(t.planned_files([("tag", "!=", "b")])) == 2


def test_partition_pruning_bucket(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA,
                            partition_by=[("id", "bucket[4]")])
    t.append(make_batch(0, 200))
    files = t.planned_files()
    assert len(files) == 4  # one file per bucket
    tr = parse_transform("bucket[4]")
    want_bucket = tr.apply(17)
    pruned = t.planned_files([("id", "=", 17)])
    assert len(pruned) == 1
    got = t.to_df(filters=[("id", "=", 17)]).collect()
    assert 17 in got["id"].to_pylist()
    # every row in the surviving file hashes to the same bucket
    ids = got["id"].to_pylist()
    assert all(tr.apply(i) == want_bucket for i in ids)


def test_min_max_file_skipping(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 100))
    t.append(make_batch(100, 200))
    t.append(make_batch(200, 300))
    assert len(t.planned_files([("id", ">=", 250)])) == 1
    assert len(t.planned_files([("id", "<", 100)])) == 1
    assert len(t.planned_files([("id", "in", [50, 150])])) == 2
    got = t.to_df(filters=[("id", ">=", 250)]).collect()
    assert got.num_rows == 100  # file-level pruning only; residual rows stay


def test_time_transforms(sess, tmp_path):
    sch = T.StructType([T.StructField("d", T.DATE, True),
                        T.StructField("x", T.LONG, True)])
    t = IcebergTable.create(sess, str(tmp_path / "t"), sch,
                            partition_by=[("d", "month")])
    jan = pa.table({"d": pa.array([datetime.date(2024, 1, i)
                                   for i in range(1, 11)]),
                    "x": pa.array(range(10), type=pa.int64())})
    mar = pa.table({"d": pa.array([datetime.date(2024, 3, i)
                                   for i in range(1, 11)]),
                    "x": pa.array(range(10, 20), type=pa.int64())})
    t.append(jan)
    t.append(mar)
    assert len(t.planned_files()) == 2
    only_jan = t.planned_files(
        [("d", "=", datetime.date(2024, 1, 5))])
    assert len(only_jan) == 1
    lt_feb = t.planned_files(
        [("d", "<", datetime.date(2024, 2, 1))])
    assert len(lt_feb) == 1


def test_schema_evolution_rename_add_drop(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 10))
    # rename: old files resolve by field id
    t.rename_column("v", "value")
    df = t.to_df().orderBy("id").collect()
    assert "value" in df.column_names
    assert df["value"].to_pylist()[:3] == [0.0, 0.5, 1.0]
    # add: old files null-fill
    t.add_column("extra", T.LONG)
    df = t.to_df().collect()
    assert df["extra"].null_count == 10
    # new writes carry the new schema
    t.append(pa.table({
        "id": pa.array([100, 101], type=pa.int64()),
        "value": pa.array([1.0, 2.0]),
        "tag": ["z", "z"],
        "extra": pa.array([7, 8], type=pa.int64())}))
    df = t.to_df().orderBy("id").collect()
    assert df["extra"].to_pylist()[-2:] == [7, 8]
    # drop
    t.drop_column("tag")
    df = t.to_df().collect()
    assert "tag" not in df.column_names
    # old snapshots still read with their own schema (time travel)
    first_snap = t.meta.snapshots[0].snapshot_id
    old = t.to_df(snapshot_id=first_snap).collect()
    assert "v" in old.column_names and "tag" in old.column_names


def test_position_deletes(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 100))
    n = t.delete_where(("id", "<", 10))
    assert n == 10
    df = t.to_df().orderBy("id").collect()
    assert df.num_rows == 90
    assert df["id"].to_pylist()[0] == 10
    # delete is a snapshot: time travel sees the old rows
    pre_delete = t.meta.snapshots[0].snapshot_id
    old = t.to_df(snapshot_id=pre_delete).collect()
    assert old.num_rows == 100
    # second delete composes with the first
    n2 = t.delete_where(("id", ">=", 95))
    assert n2 == 5
    assert t.to_df().count() == 85
    # deleting already-deleted rows is a no-op
    assert t.delete_where(("id", "<", 10)) == 0


def test_expire_snapshots(sess, tmp_path):
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 10))
    t.append(make_batch(10, 20))
    t.append(make_batch(20, 30))
    assert len(t.meta.snapshots) == 3
    removed = t.expire_snapshots(older_than_ms=int(time.time() * 1000) + 10)
    assert removed == 2  # all but current
    assert len(t.meta.snapshots) == 1
    assert t.to_df().count() == 30
    # reload from disk and confirm persisted
    t2 = IcebergTable.for_path(sess, str(tmp_path / "t"))
    assert len(t2.meta.snapshots) == 1


def test_engine_query_over_iceberg(sess, tmp_path):
    """End-to-end: engine aggregation over a pruned iceberg scan."""
    from spark_rapids_tpu.sql import functions as F
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA,
                            partition_by=[("tag", "identity")])
    t.append(make_batch(0, 50, "a"))
    t.append(make_batch(50, 100, "b"))
    df = t.to_df(filters=[("tag", "=", "b")])
    out = (df.groupBy("tag")
           .agg(F.sum(F.col("id")).alias("s"),
                F.count("*").alias("c")).collect())
    assert out.num_rows == 1
    assert out["s"].to_pylist() == [sum(range(50, 100))]
    assert out["c"].to_pylist() == [50]


def test_concurrent_commit_detected(sess, tmp_path):
    """A writer holding stale metadata must get ConcurrentCommitException,
    not silently drop the other writer's snapshot."""
    from spark_rapids_tpu.iceberg import ConcurrentCommitException
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 10))
    a = IcebergTable.for_path(sess, str(tmp_path / "t"))
    b = IcebergTable.for_path(sess, str(tmp_path / "t"))
    a.append(make_batch(10, 20))
    with pytest.raises(ConcurrentCommitException):
        b.append(make_batch(20, 30))
    # loser refreshes and retries; winner's rows survive
    b.refresh().append(make_batch(20, 30))
    assert IcebergTable.for_path(sess, str(tmp_path / "t")).to_df().count() == 30


def test_identity_partition_on_date(sess, tmp_path):
    sch = T.StructType([T.StructField("d", T.DATE, True),
                        T.StructField("x", T.LONG, True)])
    t = IcebergTable.create(sess, str(tmp_path / "t"), sch,
                            partition_by=[("d", "identity")])
    d1, d2 = datetime.date(2024, 1, 1), datetime.date(2024, 2, 1)
    t.append(pa.table({"d": pa.array([d1, d1, d2]),
                       "x": pa.array([1, 2, 3], type=pa.int64())}))
    assert len(t.planned_files()) == 2
    assert len(t.planned_files([("d", "=", d1)])) == 1
    got = t.to_df(filters=[("d", "=", d1)]).collect()
    assert sorted(got["x"].to_pylist()) == [1, 2]


def test_metadata_tables_and_compaction(sess, tmp_path):
    import pyarrow as pa

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.iceberg import IcebergTable
    schema = T.StructType((T.StructField("id", T.LONG, False),
                           T.StructField("v", T.DOUBLE, True)))
    tab = IcebergTable.create(sess, str(tmp_path / "ice"), schema)
    for i in range(3):
        tab.append(pa.table({"id": pa.array([i * 10, i * 10 + 1],
                                            type=pa.int64()),
                             "v": [1.0 * i, 2.0 * i]}))
    snaps = tab.snapshots_df().collect().to_pandas()
    assert len(snaps) == 3 and set(snaps["operation"]) == {"append"}
    files = tab.files_df().collect().to_pandas()
    assert len(files) == 3
    assert files["record_count"].sum() == 6
    # delete one row, then compact everything into one file
    tab.delete_where(("id", "=", 21))
    compacted = tab.rewrite_data_files(target_files=1)
    assert compacted == 3
    tab = tab.refresh()
    files = tab.files_df().collect().to_pandas()
    assert len(files) == 1
    out = tab.to_df().collect().to_pandas().sort_values("id")
    assert list(out["id"]) == [0, 1, 10, 11, 20]
    # history keeps all operations incl. the replace
    ops = [h["operation"] for h in tab.history()]
    assert ops[-1] == "replace" and "delete" in ops


def test_normalize_data_path_remote_schemes():
    """Real Iceberg metadata commonly stores s3:// / hdfs:// / gs://
    location URIs; they are not absolute OS paths, so they must take the
    data/ / metadata/ suffix fallback rather than coming back verbatim
    (advisor r3 — a verbatim URI joined under the table root produced an
    opaque read error)."""
    from spark_rapids_tpu.iceberg.metadata import normalize_data_path
    root = "/tmp/tbl"
    assert normalize_data_path(
        "s3://bkt/wh/tbl/data/p=1/f.parquet", root) == "data/p=1/f.parquet"
    assert normalize_data_path(
        "hdfs://nn:8020/wh/tbl/metadata/m.avro", root) == "metadata/m.avro"
    assert normalize_data_path(
        "gs://b/x/data/f.parquet", root) == "data/f.parquet"
    with pytest.raises(ValueError, match="unsupported"):
        normalize_data_path("s3://bkt/elsewhere/f.parquet", root)


def test_trivial_scan_rides_device_decode(sess, tmp_path):
    """A deletes-free, evolution-free scan routes through FileScanExec
    and its device parquet decode (table._trivial_scan_paths) instead of
    the host assembly path — and still matches it exactly."""
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 4000))
    t.append(make_batch(4000, 8000, tag="b"))
    got = t.to_df().orderBy("id").collect()
    assert t.last_scan_file_stats == {"device": 2, "host": 0}
    m = sess.last_query_metrics
    assert m.get("parquetDeviceDecodedColumns", 0) > 0, m
    assert got["id"].to_pylist() == list(range(8000))

    # a position delete flips the scan back to the host assembly path
    t.delete_where(("id", "=", 7))
    after = t.to_df().collect()
    assert t.last_scan_file_stats is None
    assert after.num_rows == 7999


def test_partial_device_decode_after_drop_readd(sess, tmp_path):
    """Drop+re-add of a column allocates a fresh field id; the OLD file's
    stale same-named values must null-fill while its untouched columns
    STILL ride the device decode (VERDICT r4 #8 — round 4 declined the
    whole scan).  The new file device-decodes fully."""
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 3000))
    t = t.drop_column("v").add_column("v", T.DOUBLE)
    t.append(make_batch(3000, 5000, tag="b"))

    df = t.to_df()
    assert t.last_scan_file_stats == {"device": 2, "host": 0}, \
        t.last_scan_file_stats
    got = df.orderBy("id").collect()
    m = sess.last_query_metrics
    assert m.get("parquetDeviceDecodedColumns", 0) > 0, m
    assert got["id"].to_pylist() == list(range(5000))
    vs = got["v"].to_pylist()
    assert all(x is None for x in vs[:3000])      # stale ids null-fill
    assert all(x is not None for x in vs[3000:])  # new file's real values


def test_partial_device_decode_after_rename(sess, tmp_path):
    """A renamed column keeps its field id: old files device-decode and
    project the old physical name onto the new one."""
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 2000))
    t = t.rename_column("v", "value")
    df = t.to_df()
    assert t.last_scan_file_stats["host"] == 0
    got = df.orderBy("id").collect()
    assert "value" in got.column_names
    assert sess.last_query_metrics.get("parquetDeviceDecodedColumns",
                                       0) > 0
    exp = make_batch(0, 2000)
    assert got["value"].to_pylist() == exp["v"].to_pylist()


def test_partial_device_decode_matches_host_path(sess, tmp_path):
    """Evolution mix (drop+re-add, rename, add) — the device-projected
    union must equal the host assembly path row-for-row."""
    t = IcebergTable.create(sess, str(tmp_path / "t"), SCHEMA)
    t.append(make_batch(0, 1500))
    t = t.rename_column("tag", "label").add_column("extra", T.LONG)
    t.append(pa.table({
        "id": pa.array(range(1500, 2500), type=pa.int64()),
        "v": pa.array([float(i) for i in range(1000)]),
        "label": pa.array(["x"] * 1000),
        "extra": pa.array(range(1000), type=pa.int64()),
    }))
    got = t.to_df().orderBy("id").collect()
    # host oracle: the id-resolving assembly reader
    parts = t.scan((), None, None)
    host = pa.concat_tables(parts).sort_by("id")
    assert got.column_names == host.column_names
    for c in host.column_names:
        assert got[c].to_pylist() == host[c].to_pylist(), c
    # a delete still flips the whole scan to host assembly
    t.delete_where(("id", "=", 3))
    assert t._device_scan_df((), None, None) is None
