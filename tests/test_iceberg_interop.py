"""Interop: read an Iceberg v2 table the engine did NOT write.

Fixture under tests/golden/iceberg/orders is composed by
tools/make_golden_iceberg.py straight from the Iceberg table spec: real
metadata JSON keys, and avro manifest list / manifests in the REAL nested
``manifest_file`` / ``manifest_entry{data_file: r2{...}}`` layout written
by an independent from-scratch avro encoder (VERDICT r2 #5)."""

import os

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.iceberg import IcebergTable

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "iceberg",
                      "orders")


@pytest.fixture()
def sess():
    return srt.session()


def test_foreign_current_snapshot_applies_position_deletes(sess):
    t = IcebergTable.for_path(sess, GOLDEN)
    got = t.to_df().collect().to_pandas().sort_values("order_id")
    # snapshot 1002 deletes order_id=2 (file 0, pos 1) via a position-
    # delete file
    assert list(got["order_id"]) == [1, 3, 4, 5, 6]
    assert got[got.order_id == 4].amount.iloc[0] == 5.25


def test_foreign_time_travel_by_snapshot_id(sess):
    t = IcebergTable.for_path(sess, GOLDEN)
    v1 = (t.to_df(snapshot_id=1001).collect().to_pandas()
          .sort_values("order_id"))
    assert list(v1["order_id"]) == [1, 2, 3, 4, 5, 6]


def test_foreign_time_travel_as_of_timestamp(sess):
    t = IcebergTable.for_path(sess, GOLDEN)
    old = (t.to_df(as_of_timestamp_ms=1735689650000)  # between snapshots
           .collect().to_pandas())
    assert len(old) == 6


def test_real_manifest_layout_parsed(sess):
    """The manifests on disk are the REAL nested avro layout — confirm
    the reader went through that path and recovered file sizes/counts."""
    from spark_rapids_tpu.iceberg.metadata import (read_manifest,
                                                   read_manifest_list)
    t = IcebergTable.for_path(sess, GOLDEN)
    snap = t.meta.snapshot()
    rels = read_manifest_list(GOLDEN, snap.manifest_list)
    assert len(rels) == 2
    entries = [e for rel in rels for e in read_manifest(GOLDEN, rel)]
    data = [e for e in entries if e.data_file.content == 0]
    dels = [e for e in entries if e.data_file.content == 1]
    assert len(data) == 2 and len(dels) == 1
    assert all(e.data_file.record_count > 0 for e in entries)
    assert all(e.data_file.file_size > 0 for e in entries)


def test_history_and_snapshots(sess):
    t = IcebergTable.for_path(sess, GOLDEN)
    ops = [h["operation"] for h in t.history()]
    assert ops == ["append", "delete"]


def test_foreign_equality_deletes(sess):
    """orders_eqdel golden fixture: a foreign v2 table whose second
    snapshot commits an EQUALITY delete (field id 1 = order_id, ids 2 and
    5, written under a HISTORICAL column name so only field-id matching
    finds it).  The scan must drop exactly those rows (reference
    GpuDeleteFilter.java:94 equalityFieldIds)."""
    t = IcebergTable.for_path(
        sess, os.path.join(os.path.dirname(GOLDEN), "orders_eqdel"))
    df = t.to_df()
    got = df.collect().to_pandas().sort_values("order_id")
    assert list(got["order_id"]) == [1, 3, 4, 6]
    assert list(got["amount"]) == [10.0, 30.0, 5.25, 42.0]


def test_engine_equality_delete_roundtrip(sess, tmp_path):
    """Engine-written equality deletes: delete_where_equality commits an
    EQUALITY_DELETES file; a fresh reader applies it.  Data appended
    AFTER the delete (higher sequence number) is NOT affected —
    sequence-number scoping, the part position deletes don't have."""
    import pyarrow as pa
    from spark_rapids_tpu import types as T2
    path = str(tmp_path / "eqtbl")
    t = IcebergTable.create(sess, path, T2.StructType((
        T2.StructField("id", T2.LONG, True),
        T2.StructField("v", T2.DOUBLE, True))))
    t.append(pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                       "v": [1.0, 2.0, 3.0]}))
    t.delete_where_equality(pa.table({"id": pa.array([2], pa.int64())}))
    # re-append id=2 AFTER the delete: must survive (newer sequence)
    t.append(pa.table({"id": pa.array([2], pa.int64()), "v": [99.0]}))
    fresh = IcebergTable.for_path(sess, path)
    got = fresh.to_df().collect().to_pandas().sort_values(["id", "v"])
    assert list(got["id"]) == [1, 2, 3]
    assert list(got["v"]) == [1.0, 99.0, 3.0]


def test_equality_delete_survives_rename(sess, tmp_path):
    """The delete file is stamped with PARQUET:field_id, so the delete
    keeps applying after the key column is renamed (field-id resolution,
    like foreign readers)."""
    import pyarrow as pa
    from spark_rapids_tpu import types as T2
    path = str(tmp_path / "rn")
    t = IcebergTable.create(sess, path, T2.StructType((
        T2.StructField("id", T2.LONG, True),
        T2.StructField("v", T2.DOUBLE, True))))
    t.append(pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                       "v": [1.0, 2.0, 3.0]}))
    t.delete_where_equality(pa.table({"id": pa.array([2], pa.int64())}))
    t.rename_column("id", "ident")
    got = (IcebergTable.for_path(sess, path).to_df()
           .collect().to_pandas().sort_values("ident"))
    assert list(got["ident"]) == [1, 3]


def test_delete_where_skips_eq_deleted_rows(sess, tmp_path):
    """delete_where must not count (or re-delete) rows an equality
    delete already removed (review r4 finding)."""
    import pyarrow as pa
    from spark_rapids_tpu import types as T2
    path = str(tmp_path / "dw")
    t = IcebergTable.create(sess, path, T2.StructType((
        T2.StructField("id", T2.LONG, True),)))
    t.append(pa.table({"id": pa.array([1, 2, 3], pa.int64())}))
    t.delete_where_equality(pa.table({"id": pa.array([2], pa.int64())}))
    n = t.delete_where(("id", "=", 2))
    assert n == 0, n
