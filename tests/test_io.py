"""I/O tests: readers (incl. the in-repo Avro container parser) and the
write stack (dynamic partitioning, save modes, stats) — reference coverage
model: integration_tests parquet/orc/csv/json/avro round-trip suites."""

import datetime
import json
import os

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt


@pytest.fixture()
def sess():
    return srt.session()


def sample_table(n=100, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array(rng.integers(-1000, 1000, n), type=pa.int64()),
        "f": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([f"row-{k}" if k % 7 else None for k in range(n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "part": pa.array(rng.integers(0, 3, n), type=pa.int32()),
    })


FORMATS = ["parquet", "orc", "csv", "json", "avro"]


@pytest.mark.parametrize("fmt", FORMATS)
def test_write_read_roundtrip(sess, fmt, tmp_path):
    t = sample_table()
    df = sess.create_dataframe(t)
    out = str(tmp_path / f"out_{fmt}")
    stats = getattr(df.write.mode("overwrite"), fmt)(out)
    assert stats.num_rows == t.num_rows
    assert stats.num_files >= 1
    assert os.path.exists(os.path.join(out, "_SUCCESS"))

    back = getattr(sess.read, fmt)(out).collect()
    assert back.num_rows == t.num_rows
    gi = sorted(back.column("i").to_pylist())
    assert gi == sorted(t.column("i").to_pylist())
    got_f = sorted(x for x in back.column("f").to_pylist())
    exp_f = sorted(t.column("f").to_pylist())
    assert np.allclose(got_f, exp_f)
    # strings: csv cannot distinguish null from empty; allow either there
    got_s = sorted((x or "") for x in back.column("s").to_pylist())
    exp_s = sorted((x or "") for x in t.column("s").to_pylist())
    assert got_s == exp_s


def test_dynamic_partitioned_write(sess, tmp_path):
    t = sample_table()
    df = sess.create_dataframe(t)
    out = str(tmp_path / "pq_parts")
    stats = df.write.mode("overwrite").partitionBy("part").parquet(out)
    dirs = sorted(d for d in os.listdir(out) if d.startswith("part="))
    assert dirs == ["part=0", "part=1", "part=2"]
    assert sorted(stats.partition_paths) == dirs
    # read back one partition dir: data columns only
    sub = sess.read.parquet(os.path.join(out, "part=1")).collect()
    assert "part" not in sub.column_names
    mask = np.asarray(t.column("part")) == 1
    assert sub.num_rows == int(mask.sum())


def test_save_modes(sess, tmp_path):
    t = sample_table(20)
    df = sess.create_dataframe(t)
    out = str(tmp_path / "modes")
    df.write.parquet(out)
    with pytest.raises(FileExistsError):
        df.write.parquet(out)
    df.write.mode("ignore").parquet(out)  # no-op
    df.write.mode("append").parquet(out)
    assert sess.read.parquet(out).collect().num_rows == 2 * t.num_rows
    df.write.mode("overwrite").parquet(out)
    assert sess.read.parquet(out).collect().num_rows == t.num_rows


def test_avro_reader_features(tmp_path):
    """Exercise the container parser directly: deflate codec, nullable
    unions, logical date/timestamp types, multi-block files."""
    from spark_rapids_tpu.io_.avro_reader import read_avro, write_avro

    t = pa.table({
        "id": pa.array(range(500), type=pa.int64()),
        "name": pa.array([None if i % 9 == 0 else f"n{i}" for i in range(500)]),
        "d": pa.array([datetime.date(2020, 1, 1) + datetime.timedelta(days=i)
                       for i in range(500)]),
        "ts": pa.array([datetime.datetime(2021, 5, 4, 3, 2, 1)
                        + datetime.timedelta(seconds=i) for i in range(500)],
                       type=pa.timestamp("us")),
        "tags": pa.array([[f"t{i}", "x"] if i % 2 else []
                          for i in range(500)]),
    })
    path = str(tmp_path / "f.avro")
    write_avro(t, path)
    back = read_avro(path)
    assert back.column("id").to_pylist() == list(range(500))
    assert back.column("name").to_pylist() == t.column("name").to_pylist()
    assert back.column("d").to_pylist() == t.column("d").to_pylist()
    assert back.column("ts").to_pylist() == t.column("ts").to_pylist()
    assert back.column("tags").to_pylist() == t.column("tags").to_pylist()


def test_avro_deflate_interop(tmp_path):
    """If the avro python package (or fastavro) is around, cross-check;
    otherwise verify our deflate read path against a hand-built file."""
    import struct
    import zlib

    # hand-build a 2-block deflate file with one long field
    schema = {"type": "record", "name": "r",
              "fields": [{"name": "v", "type": "long"}]}

    def zz(v):
        out = bytearray()
        u = ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1)
        while True:
            b = u & 0x7F
            u >>= 7
            out.append(b | 0x80 if u else b)
            if not u:
                return bytes(out)

    sync = b"0123456789abcdef"
    hdr = bytearray(b"Obj\x01")
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"deflate"}
    hdr += zz(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        hdr += zz(len(kb)) + kb + zz(len(v)) + v
    hdr += zz(0) + sync
    body = bytearray()
    for block_vals in ([1, 2, 3], [40, 50]):
        raw = b"".join(zz(v) for v in block_vals)
        comp = zlib.compress(raw)[2:-4]  # raw deflate
        body += zz(len(block_vals)) + zz(len(comp)) + comp + sync
    path = str(tmp_path / "d.avro")
    with open(path, "wb") as fh:
        fh.write(bytes(hdr) + bytes(body))

    from spark_rapids_tpu.io_.avro_reader import read_avro
    back = read_avro(path)
    assert back.column("v").to_pylist() == [1, 2, 3, 40, 50]


def test_write_from_query(sess, tmp_path):
    """Write the output of a device-side query (scan->filter->agg->write)."""
    from spark_rapids_tpu.sql import functions as F
    t = sample_table(1000)
    df = sess.create_dataframe(t)
    q = (df.filter(df.i > 0).groupBy("part")
         .agg(F.sum(F.col("i")).alias("s"), F.count("*").alias("c")))
    out = str(tmp_path / "agg_out")
    stats = q.write.mode("overwrite").parquet(out)
    assert stats.num_rows <= 3
    back = sess.read.parquet(out).collect()
    import pandas as pd
    pdf = t.to_pandas()
    pdf = pdf[pdf.i > 0].groupby("part").agg(s=("i", "sum"), c=("i", "count"))
    got = back.to_pandas().set_index("part").sort_index()
    assert (got["s"] == pdf["s"]).all()
    assert (got["c"] == pdf["c"]).all()
