"""I/O depth: parquet row-group pruning from pushed filters, chunked
reads, the local file cache, and path-replacement rules (reference
GpuParquetScan footer pruning, chunked readers RapidsConf.scala:568,
file-cache feature, AlluxioUtils path replacement)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def write_parquet(path, n=100_000, row_group_size=10_000):
    t = pa.table({
        "id": pa.array(range(n), type=pa.int64()),
        "v": pa.array(np.arange(n, dtype=np.float64) * 0.5),
        "s": [f"r{i:06d}" for i in range(n)],
    })
    pq.write_table(t, str(path), row_group_size=row_group_size)
    return t


def test_row_group_pruning_metrics_and_results(sess, tmp_path):
    p = tmp_path / "t.parquet"
    write_parquet(p)
    df = sess.read.parquet(str(p))
    q = df.filter(df.id >= 95_000)
    assert "pushed=" in sess.explain(q)
    out = q.collect()
    assert out.num_rows == 5_000
    m = sess.last_query_metrics
    assert m.get("rowGroupsTotal", 0) == 10
    assert m.get("rowGroupsPruned", 0) == 9  # only the last group survives
    assert sorted(out["id"].to_pylist()) == list(range(95_000, 100_000))


def test_pruning_never_changes_results(sess, tmp_path):
    p = tmp_path / "t.parquet"
    write_parquet(p, n=50_000, row_group_size=7_000)
    df = sess.read.parquet(str(p))
    on = df.filter((df.id >= 11_111) & (df.id < 33_333)).collect()
    sess2 = srt.session(**{
        "spark.rapids.sql.format.parquet.filterPushdown.enabled": False})
    df2 = sess2.read.parquet(str(p))
    off = df2.filter((df2.id >= 11_111) & (df2.id < 33_333)).collect()
    assert sorted(on["id"].to_pylist()) == sorted(off["id"].to_pylist())
    assert on.num_rows == 33_333 - 11_111


def test_pruning_all_groups_empty_result(sess, tmp_path):
    p = tmp_path / "t.parquet"
    write_parquet(p, n=1_000, row_group_size=100)
    df = sess.read.parquet(str(p))
    out = df.filter(df.id > 10_000_000).collect()
    assert out.num_rows == 0
    assert set(out.column_names) == {"id", "v", "s"}


def test_chunked_read_multiple_batches(sess, tmp_path):
    p = tmp_path / "t.parquet"
    write_parquet(p, n=60_000, row_group_size=5_000)
    s = srt.session(**{
        "spark.rapids.sql.reader.chunked": True,
        "spark.rapids.sql.reader.chunked.targetRows": 20_000})
    df = s.read.parquet(str(p))
    out = df.collect()
    assert out.num_rows == 60_000
    assert s.last_query_metrics.get("chunkedReadBatches", 0) == 3
    # aggregate over chunked scan stays exact
    agg = df.agg(F.sum(F.col("id")).alias("s")).collect()
    assert agg["s"].to_pylist() == [sum(range(60_000))]


def test_file_cache_hit_and_reuse(tmp_path):
    from spark_rapids_tpu.io_ import filecache as FC
    FC.FileCache.reset()
    p = tmp_path / "t.parquet"
    write_parquet(p, n=1_000, row_group_size=500)
    s = srt.session(**{
        "spark.rapids.filecache.enabled": True,
        "spark.rapids.filecache.path": str(tmp_path / "cache")})
    before = dict(FC.STATS)
    assert s.read.parquet(str(p)).count() == 1_000
    assert s.read.parquet(str(p)).count() == 1_000
    assert FC.STATS["misses"] - before["misses"] >= 1
    assert FC.STATS["hits"] - before["hits"] >= 1
    assert os.listdir(str(tmp_path / "cache"))
    FC.FileCache.reset()


def test_file_cache_invalidated_on_change(tmp_path):
    from spark_rapids_tpu.io_ import filecache as FC
    FC.FileCache.reset()
    p = tmp_path / "t.parquet"
    write_parquet(p, n=100, row_group_size=50)
    s = srt.session(**{
        "spark.rapids.filecache.enabled": True,
        "spark.rapids.filecache.path": str(tmp_path / "cache")})
    assert s.read.parquet(str(p)).count() == 100
    # rewrite with different contents -> new mtime/size -> fresh copy
    t2 = pa.table({"id": pa.array(range(7), type=pa.int64()),
                   "v": pa.array([0.0] * 7),
                   "s": ["x"] * 7})
    os.remove(str(p))
    pq.write_table(t2, str(p))
    assert s.read.parquet(str(p)).count() == 7
    FC.FileCache.reset()


def test_path_rewrite_rules(tmp_path):
    from spark_rapids_tpu.io_.filecache import rewrite_path
    p = tmp_path / "t.parquet"
    write_parquet(p, n=10, row_group_size=5)
    s = srt.session(**{
        "spark.rapids.tpu.io.replacePaths":
            f"s3://bucket/data->{tmp_path}"})
    # the configured prefix rewrites to the local dir and the read works
    assert rewrite_path("s3://bucket/data/t.parquet", s.conf) == \
        str(tmp_path / "t.parquet")
    unchanged = rewrite_path("/local/t.parquet", s.conf)
    assert unchanged == "/local/t.parquet"


def test_orc_chunked_read(tmp_path):
    import pyarrow.orc as orc
    t = pa.table({"x": pa.array(range(100_000), type=pa.int64())})
    orc.write_table(t, str(tmp_path / "t.orc"), stripe_size=64 * 1024)
    s = srt.session(**{
        "spark.rapids.sql.reader.chunked": True,
        "spark.rapids.sql.reader.chunked.targetRows": 20_000})
    df = s.read.orc(str(tmp_path / "t.orc"))
    out = df.agg(F.sum(F.col("x")).alias("s"),
                 F.count("*").alias("c")).collect()
    assert out["s"].to_pylist() == [sum(range(100_000))]
    assert out["c"].to_pylist() == [100_000]
    assert s.last_query_metrics.get("chunkedReadBatches", 0) >= 2


def test_coalescing_device_decode(tmp_path):
    """COALESCING scans device-decode per file and concat ON DEVICE
    (round 5 — previously host-concat only); mismatched schemas fall
    back to the host promote-concat path."""
    import numpy as np
    import pyarrow.parquet as pq

    import spark_rapids_tpu as srt
    rng = np.random.default_rng(5)
    for i in range(4):
        t = pa.table({"k": pa.array(rng.integers(0, 50, 800)),
                      "s": pa.array([f"f{i}-{j % 19}" for j in range(800)]),
                      "v": pa.array(rng.random(800))})
        pq.write_table(t, str(tmp_path / f"part-{i}.parquet"))
    sess = srt.session(**{"spark.rapids.sql.format.parquet.reader.type":
                          "COALESCING"})
    df = sess.read.parquet(str(tmp_path))
    got = df.collect()
    assert got.num_rows == 3200
    m = sess.last_query_metrics
    assert m.get("coalescedDeviceConcat", 0) >= 1, m
    assert m.get("parquetDeviceDecodedColumns", 0) >= 3, m
    # correctness vs plain per-file read
    sess2 = srt.session()
    want = sess2.read.parquet(str(tmp_path)).orderBy("k", "s", "v").collect()
    got2 = sess.read.parquet(str(tmp_path)).orderBy("k", "s", "v").collect()
    for c in want.column_names:
        assert got2.column(c).to_pylist() == want.column(c).to_pylist(), c
