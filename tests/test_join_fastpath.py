"""Join fast path — cached build-side sort + probe-only tuple search +
speculative output sizing (ISSUE 2 tentpole).

Parity contract: the cached-build path must be bit-identical to the
union-rank path across every join type, null handling mode, string and
multi-column keys.  Efficiency contract: ONE build-side sort per build
batch and at most ONE blocking host readback per probe batch when output
speculation hits.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar import arrow_to_device
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.ops import join as OJ
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical import join as PJ


@pytest.fixture()
def sess():
    return srt.session()


def _sess_with(overrides):
    return srt.session(conf=RapidsConf.get_global().copy(
        {k: str(v) for k, v in overrides.items()}))


# --------------------------------------------------------------------------
# ops-level parity: union-rank join_build vs prepare_build_side + probe
# --------------------------------------------------------------------------

def _key_batches(kind):
    if kind == "int":
        l = pa.table({"k": pa.array([1, 2, 2, None, 7, 5, 2],
                                    type=pa.int64())})
        r = pa.table({"k": pa.array([2, 2, None, 5, 9],
                                    type=pa.int64())})
    elif kind == "string":
        l = pa.table({"k": pa.array(["aa", "b", None, "ccc", "b",
                                     "longer-string-key"])})
        r = pa.table({"k": pa.array(["b", None, "ccc", "zz",
                                     "longer-string-key"])})
    elif kind == "multi":
        l = pa.table({"k1": pa.array([1, 1, 2, 2, None, 3],
                                     type=pa.int64()),
                      "k2": pa.array(["x", "y", "x", None, "x", "y"])})
        r = pa.table({"k1": pa.array([1, 2, 2, None, 3],
                                     type=pa.int64()),
                      "k2": pa.array(["y", "x", "x", "x", None])})
    elif kind == "float":
        l = pa.table({"k": pa.array([1.5, float("nan"), -0.0, 2.5, None])})
        r = pa.table({"k": pa.array([0.0, float("nan"), 2.5, None])})
    else:
        raise AssertionError(kind)
    return arrow_to_device(l), arrow_to_device(r)


@pytest.mark.parametrize("kind", ["int", "string", "multi", "float"])
@pytest.mark.parametrize("null_safe", [False, True])
def test_ops_parity_info_and_pairs(kind, null_safe):
    """JoinInfo match structure and every gather-map variant agree exactly
    between the two phase-1 implementations."""
    import jax.numpy as jnp
    lb, rb = _key_batches(kind)
    lmask, rmask = lb.row_mask(), rb.row_mask()
    lkeys, rkeys = list(lb.columns), list(rb.columns)

    ref = OJ.join_build(jnp, lkeys, rkeys, lmask, rmask,
                        null_safe=null_safe)
    bs = OJ.prepare_build_side(jnp, rkeys, rmask, null_safe=null_safe)
    got = OJ.probe_join_info(jnp, lkeys, lmask, rmask, bs,
                             null_safe=null_safe)

    np.testing.assert_array_equal(np.asarray(ref.counts),
                                  np.asarray(got.counts))
    np.testing.assert_array_equal(np.asarray(ref.csum),
                                  np.asarray(got.csum))
    assert int(ref.total) == int(got.total)
    np.testing.assert_array_equal(np.asarray(ref.l_unmatched),
                                  np.asarray(got.l_unmatched))
    np.testing.assert_array_equal(np.asarray(ref.b_unmatched),
                                  np.asarray(got.b_unmatched))
    assert int(ref.n_unmatched_l) == int(got.n_unmatched_l)
    assert int(ref.n_unmatched_b) == int(got.n_unmatched_b)

    out_cap = 64
    for wl, wr in ((False, False), (True, False), (True, True)):
        mref = OJ.gather_pairs(jnp, ref, out_cap, with_unmatched_left=wl,
                               with_unmatched_right=wr)
        mgot = OJ.gather_pairs(jnp, got, out_cap, with_unmatched_left=wl,
                               with_unmatched_right=wr)
        assert int(mref.num_out) == int(mgot.num_out)
        n = int(mref.num_out)
        for fld in ("l_idx", "r_idx", "l_ok", "r_ok"):
            np.testing.assert_array_equal(
                np.asarray(getattr(mref, fld))[:n],
                np.asarray(getattr(mgot, fld))[:n], err_msg=fld)


def test_tuple_searchsorted_matches_numpy():
    from spark_rapids_tpu.ops.ranks import tuple_searchsorted
    rng = np.random.default_rng(3)
    s = np.sort(rng.integers(0, 50, 257))
    q = rng.integers(-5, 60, 100)
    for side in ("left", "right"):
        got = tuple_searchsorted(np, [s], [q], side=side)
        np.testing.assert_array_equal(got, np.searchsorted(s, q, side=side))


# --------------------------------------------------------------------------
# exec-level parity: buildSideCache on vs off, all public join types
# --------------------------------------------------------------------------

_L = pa.table({
    "k": pa.array([1, 2, 2, 3, None, 5], type=pa.int64()),
    "s": pa.array(["a", "b", "b", None, "c", "d"]),
    "lv": pa.array([10, 20, 21, 30, 40, 50], type=pa.int64()),
})
_R = pa.table({
    "k": pa.array([2, 2, 3, 4, None], type=pa.int64()),
    "s": pa.array(["b", "x", None, "y", "b"]),
    "rv": pa.array([200, 201, 300, 400, 500], type=pa.int64()),
})


def _rows(df, cols):
    return sorted(
        (tuple((v is None, v) for v in (row[c] for c in cols))
         for row in df.collect().to_pylist()))


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
@pytest.mark.parametrize("keys", [["k"], ["k", "s"]])
def test_exec_parity_fast_vs_fallback(how, keys):
    out = {}
    for mode in (True, False):
        sess = _sess_with({
            "spark.rapids.sql.join.buildSideCache.enabled": mode})
        l = sess.create_dataframe(_L, num_partitions=2)
        r = sess.create_dataframe(_R, num_partitions=2)
        cond = None
        for k in keys:
            term = l[k] == r[k]
            cond = term if cond is None else cond & term
        q = l.join(r, cond, how)
        cols = [a.name for a in q._plan.output]
        out[mode] = _rows(q, cols)
    assert out[True] == out[False]


@pytest.mark.parametrize("how", ["inner", "left"])
def test_exec_parity_string_key_broadcast(how):
    out = {}
    for mode in (True, False):
        sess = _sess_with({
            "spark.rapids.sql.join.buildSideCache.enabled": mode})
        l = sess.create_dataframe(_L, num_partitions=3)
        r = sess.create_dataframe(_R.select(["s", "rv"]))
        q = l.join(r, l.s == r.s, how)
        cols = [a.name for a in q._plan.output]
        out[mode] = _rows(q, cols)
    assert out[True] == out[False]


def test_exec_parity_existence_join():
    """EXISTS under OR plans an existence join; both phase-1 paths must
    produce the same marker column."""
    out = {}
    for mode in (True, False):
        sess = _sess_with({
            "spark.rapids.sql.join.buildSideCache.enabled": mode})
        sess.create_dataframe(_L).createOrReplaceTempView("fx_l")
        sess.create_dataframe(_R).createOrReplaceTempView("fx_r")
        got = sess.sql(
            "SELECT lv FROM fx_l WHERE lv >= 40 OR EXISTS "
            "(SELECT 1 FROM fx_r WHERE fx_r.k = fx_l.k)").collect()
        out[mode] = sorted(r["lv"] for r in got.to_pylist())
    assert out[True] == out[False]
    assert out[True] == [20, 21, 30, 40, 50]


# --------------------------------------------------------------------------
# efficiency contracts
# --------------------------------------------------------------------------

def _stats_snap():
    return dict(PJ.STATS)


def _stats_delta(snap):
    return {k: PJ.STATS[k] - snap[k] for k in snap}


def test_broadcast_build_sorted_once():
    """A broadcast join with several probe partitions computes the
    build-side sort exactly once (the tentpole's headline contract)."""
    rng = np.random.default_rng(11)
    fact = pa.table({"fk": rng.integers(0, 50, 5000),
                     "x": rng.random(5000)})
    dim = pa.table({"pk": np.arange(50, dtype=np.int64),
                    "c": rng.integers(0, 4, 50)})
    sess = _sess_with({"spark.rapids.sql.adaptive.enabled": "false"})
    f = sess.create_dataframe(fact, num_partitions=4)
    d = sess.create_dataframe(dim)
    q = f.join(d, f.fk == d.pk, "inner").groupBy("c").agg(
        F.count("*").alias("n"))
    snap = _stats_snap()
    got = {r["c"]: r["n"] for r in q.collect().to_pylist()}
    delta = _stats_delta(snap)
    assert delta["build_sorts"] == 1, delta
    assert delta["fastpath_probes"] >= 4, delta
    assert delta["fallback_probes"] == 0, delta
    # oracle
    m = pd.DataFrame(fact.to_pydict()).merge(
        pd.DataFrame(dim.to_pydict()), left_on="fk", right_on="pk")
    exp = m.groupby("c").size().to_dict()
    assert got == {int(k): int(v) for k, v in exp.items()}


def test_at_most_one_readback_per_probe_batch():
    """Speculation hit => exactly one blocking readback per probe batch
    (the three sizing scalars ride one batched device_get)."""
    rng = np.random.default_rng(12)
    fact = pa.table({"fk": rng.integers(0, 64, 4096),
                     "x": rng.random(4096)})
    dim = pa.table({"pk": np.arange(64, dtype=np.int64),
                    "y": rng.random(64)})
    sess = _sess_with({"spark.rapids.sql.adaptive.enabled": "false"})
    f = sess.create_dataframe(fact, num_partitions=4)
    d = sess.create_dataframe(dim)
    q = f.join(d, f.fk == d.pk, "inner")
    snap = _stats_snap()
    n = q.collect().num_rows
    delta = _stats_delta(snap)
    assert n == 4096
    assert delta["fastpath_probes"] >= 1
    # the hard contract: no probe batch paid more than one readback
    assert delta["host_readbacks"] <= delta["fastpath_probes"] \
        + delta["fallback_probes"], delta
    assert delta["spec_misses"] == 0, delta
    assert delta["spec_hits"] == delta["fastpath_probes"], delta


def test_speculation_overflow_regathers_correctly():
    """A many-to-many join whose output overflows the predicted bucket
    must fall back to the exact re-gather — correct rows, miss counted,
    and the learned selectivity turns the NEXT run into hits."""
    l = pa.table({"k": np.repeat(np.arange(8, dtype=np.int64), 4),
                  "lv": np.arange(32, dtype=np.int64)})
    r = pa.table({"k": np.repeat(np.arange(8, dtype=np.int64), 8),
                  "rv": np.arange(64, dtype=np.int64)})
    sess = _sess_with({"spark.rapids.sql.adaptive.enabled": "false"})
    PJ._JOIN_SELECTIVITY.clear()
    ldf = sess.create_dataframe(l)
    rdf = sess.create_dataframe(r)
    q = ldf.join(rdf, ldf.k == rdf.k, "inner")
    snap = _stats_snap()
    assert q.collect().num_rows == 32 * 8  # 4x8 pairs per key, 8 keys
    delta = _stats_delta(snap)
    assert delta["spec_misses"] >= 1, delta
    snap = _stats_snap()
    assert q.collect().num_rows == 32 * 8
    delta = _stats_delta(snap)
    assert delta["spec_misses"] == 0, delta
    assert delta["spec_hits"] >= 1, delta


def test_speculation_kill_switch():
    sess = _sess_with({
        "spark.rapids.sql.join.speculativeSizing.enabled": "false",
        "spark.rapids.sql.adaptive.enabled": "false"})
    l = sess.create_dataframe(_L)
    r = sess.create_dataframe(_R)
    snap = _stats_snap()
    q = l.join(r, l.k == r.k, "inner")
    assert q.collect().num_rows == 5  # k=2: 2x2 pairs, k=3: 1
    delta = _stats_delta(snap)
    assert delta["spec_hits"] == 0 and delta["spec_misses"] == 0, delta


def test_join_stage_metrics_reported(sess):
    """last_query_metrics carries the per-stage join breakdown the bench
    artifact banks (readback/sort/search counts + stage times)."""
    l = sess.create_dataframe(_L)
    r = sess.create_dataframe(_R)
    l.join(r, l.k == r.k, "inner").collect()
    m = sess.last_query_metrics
    assert m.get("joinHostReadbacks", 0) >= 1, m
    assert any(k.startswith("joinStage") for k in m), m


def test_selectivity_cleared_with_kernel_cache():
    from spark_rapids_tpu.sql.physical.kernel_cache import clear_cache
    PJ._JOIN_SELECTIVITY[("probe-key",)] = 2.0
    clear_cache()
    assert not PJ._JOIN_SELECTIVITY
