"""Join tests — every join type on TPU vs the host engine, plus pandas
merge as an independent oracle (the reference's integration suite joins the
same frames on CPU Spark)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F

from test_dataframe import assert_tpu_and_cpu_equal


@pytest.fixture()
def sess():
    return srt.session()


def _left_table():
    return pa.table({
        "k": pa.array([1, 2, 2, 3, None, 5], type=pa.int64()),
        "lv": pa.array([10, 20, 21, 30, 40, 50], type=pa.int64()),
    })


def _right_table():
    return pa.table({
        "k": pa.array([2, 2, 3, 4, None], type=pa.int64()),
        "rv": pa.array([200, 201, 300, 400, 500], type=pa.int64()),
    })


def _none_key(rows):
    return sorted(rows, key=lambda t: tuple((v is None, v) for v in t))


def _pandas_oracle(how):
    """SQL-correct oracle (pandas merge matches NaN keys, SQL does not)."""
    l = _left_table().to_pandas()
    r = _right_table().to_pandas()
    ln, rn = l[l.k.notna()], r[r.k.notna()]
    m = ln.merge(rn, on="k", how="inner")
    rows = [(int(k), int(lv), int(rv))
            for k, lv, rv in m[["k", "lv", "rv"]].itertuples(index=False)]
    if how in ("left", "full"):
        matched = set(rn.k.dropna())
        for k, lv in l[["k", "lv"]].itertuples(index=False):
            if pd.isna(k) or k not in matched:
                rows.append((None if pd.isna(k) else int(k), int(lv), None))
    if how in ("right", "full"):
        matched = set(ln.k.dropna())
        for k, rv in r[["k", "rv"]].itertuples(index=False):
            if pd.isna(k) or k not in matched:
                rows.append((None if pd.isna(k) else int(k), None, int(rv)))
    return _none_key(rows)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
@pytest.mark.parametrize("nparts", [1, 3])
def test_equi_join_vs_pandas(sess, how, nparts):
    l = sess.create_dataframe(_left_table(), num_partitions=nparts)
    r = sess.create_dataframe(_right_table(), num_partitions=nparts)
    out = assert_tpu_and_cpu_equal(l.join(r, "k", how), sort_by=["k", "lv", "rv"])
    got = _none_key([
        tuple(None if v is None else int(v) for v in (row["k"], row["lv"],
                                                      row["rv"]))
        for row in out.to_pylist()])
    assert got == _pandas_oracle(how)


@pytest.mark.parametrize("how", ["left_semi", "left_anti"])
def test_semi_anti_join(sess, how):
    l = sess.create_dataframe(_left_table())
    r = sess.create_dataframe(_right_table())
    out = assert_tpu_and_cpu_equal(l.join(r, "k", how), sort_by=["lv"])
    lvs = sorted(row["lv"] for row in out.to_pylist())
    if how == "left_semi":
        assert lvs == [20, 21, 30]  # k in {2, 3}; nulls never match
    else:
        assert lvs == [10, 40, 50]  # k=1, k=None, k=5


def test_cross_join(sess):
    l = sess.create_dataframe(pa.table({"a": [1, 2, 3]}))
    r = sess.create_dataframe(pa.table({"b": [10, 20]}))
    out = assert_tpu_and_cpu_equal(l.crossJoin(r), sort_by=["a", "b"])
    assert len(out) == 6


def test_join_with_condition(sess):
    l = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 2], "x": [1, 5, 1, 5]}))
    r = sess.create_dataframe(pa.table({
        "k2": [1, 2], "y": [3, 3]}))
    cond = (F.col("k") == F.col("k2")) & (F.col("x") < F.col("y"))
    out = assert_tpu_and_cpu_equal(l.join(r, cond, "inner"),
                                   sort_by=["k", "x"])
    rows = [(row["k"], row["x"]) for row in out.to_pylist()]
    assert sorted(rows) == [(1, 1), (2, 1)]


def test_left_join_with_condition(sess):
    l = sess.create_dataframe(pa.table({"k": [1, 2, 3], "x": [0, 9, 0]}))
    r = sess.create_dataframe(pa.table({"k2": [1, 2, 3], "y": [5, 5, 5]}))
    cond = (F.col("k") == F.col("k2")) & (F.col("x") < F.col("y"))
    out = assert_tpu_and_cpu_equal(l.join(r, cond, "left"),
                                   sort_by=["k", "x"])
    rows = sorted((row["k"], row["y"]) for row in out.to_pylist())
    # k=2 fails the residual (9 < 5 false) -> null right side
    assert rows == [(1, 5), (2, None), (3, 5)]


def test_string_key_join(sess):
    l = sess.create_dataframe(pa.table({
        "name": ["alice", "bob", "carol", None],
        "v": [1, 2, 3, 4]}))
    r = sess.create_dataframe(pa.table({
        "name": ["bob", "carol", "dave", None],
        "w": [20, 30, 40, 50]}))
    out = assert_tpu_and_cpu_equal(l.join(r, "name", "inner"),
                                   sort_by=["name"])
    rows = sorted((row["name"], row["v"], row["w"])
                  for row in out.to_pylist())
    assert rows == [("bob", 2, 20), ("carol", 3, 30)]


def test_many_to_many_join(sess):
    rng = np.random.default_rng(7)
    lk = rng.integers(0, 20, 300)
    rk = rng.integers(0, 20, 200)
    l = sess.create_dataframe(pa.table({
        "k": lk, "lv": np.arange(300)}), num_partitions=4)
    r = sess.create_dataframe(pa.table({
        "k": rk, "rv": np.arange(200)}), num_partitions=2)
    out = assert_tpu_and_cpu_equal(l.join(r, "k", "inner"),
                                   sort_by=["k", "lv", "rv"])
    expected = pd.DataFrame({"k": lk, "lv": np.arange(300)}).merge(
        pd.DataFrame({"k": rk, "rv": np.arange(200)}), on="k")
    assert len(out) == len(expected)
    got = sorted(map(tuple, out.to_pydict().values().__iter__().__next__()
                 .__class__ and [
        (row["k"], row["lv"], row["rv"]) for row in out.to_pylist()]))
    exp = sorted(map(tuple, expected[["k", "lv", "rv"]].itertuples(
        index=False)))
    assert got == exp


def test_broadcast_join_path(sess):
    """Small build side + partitioned probe -> broadcast hash join."""
    l = sess.create_dataframe(pa.table({
        "k": np.arange(100) % 10, "lv": np.arange(100)}), num_partitions=4)
    r = sess.create_dataframe(pa.table({
        "k": np.arange(5), "rv": np.arange(5) * 100}))
    df = l.join(r, "k", "inner")
    from spark_rapids_tpu.sql.planner import Planner
    plan = Planner(sess._conf).plan(df._plan).tree_string()
    assert "BroadcastHashJoin" in plan
    out = assert_tpu_and_cpu_equal(df, sort_by=["k", "lv"])
    assert len(out) == 50


def test_join_then_aggregate(sess):
    """TPC-H-style join + groupby pipeline."""
    l = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0]}),
        num_partitions=2)
    r = sess.create_dataframe(pa.table({
        "k": [1, 2, 3], "grp": ["a", "b", "a"]}))
    df = (l.join(r, "k", "inner")
          .groupBy("grp").agg(F.sum("v").alias("s")))
    out = assert_tpu_and_cpu_equal(df, sort_by=["grp"])
    rows = {row["grp"]: row["s"] for row in out.to_pylist()}
    assert rows == {"a": 8.0, "b": 7.0}


def test_outer_nested_loop_empty_build(sess):
    """Left no-key join against an empty build side must keep every probe
    row (regression: out_cap was sized without unmatched slack)."""
    l = sess.create_dataframe(pa.table({"a": list(range(20))}))
    r = sess.create_dataframe(pa.table({"b": pa.array([], type=pa.int64())}))
    out = assert_tpu_and_cpu_equal(l.join(r, None, "left"), sort_by=["a"])
    assert len(out) == 20
    assert all(row["b"] is None for row in out.to_pylist())


def test_right_join_column_order(sess):
    """USING-column right join keeps pyspark's column order."""
    l = sess.create_dataframe(pa.table({"k": [1, 2], "lv": [10, 20]}))
    r = sess.create_dataframe(pa.table({"k": [2, 3], "rv": [200, 300]}))
    out = assert_tpu_and_cpu_equal(l.join(r, "k", "right"), sort_by=["k"])
    assert out.column_names == ["k", "lv", "rv"]
    rows = _none_key([(row["k"], row["lv"], row["rv"])
                      for row in out.to_pylist()])
    assert rows == [(2, 20, 200), (3, None, 300)]


def test_when_otherwise_string_literals(sess):
    """F.when value-position strings are literals, not column names."""
    df = sess.create_dataframe(pa.table({"a": [5, 15]}))
    out = df.select(F.when(F.col("a") > 10, "big")
                    .otherwise("small").alias("sz")).collect()
    assert out.column("sz").to_pylist() == ["small", "big"]


def test_full_join_nulls_both_sides(sess):
    l = sess.create_dataframe(pa.table({
        "k": pa.array([None, None, 1], type=pa.int64()),
        "lv": [1, 2, 3]}))
    r = sess.create_dataframe(pa.table({
        "k": pa.array([None, 2], type=pa.int64()),
        "rv": [10, 20]}))
    out = assert_tpu_and_cpu_equal(l.join(r, "k", "full"),
                                   sort_by=["lv", "rv"])
    # nulls never match: 3 unmatched left + 2 unmatched right + 0 matches
    assert len(out) == 5


# ---------------------------------------------------------------------------
# bloom-filter join runtime filters (GpuBloomFilterMightContain analog)
# ---------------------------------------------------------------------------

def _star_shapes(rng, n_fact=300_000, n_dim=400, key_space=80_000):
    fact = pa.table({"fk": rng.integers(0, key_space, n_fact),
                     "x": rng.random(n_fact)})
    pks = rng.choice(key_space, size=n_dim, replace=False)
    dim = pa.table({"pk": pks.astype(np.int64),
                    "name": [f"d{i}" for i in range(n_dim)]})
    return fact, dim


def test_bloom_star_join_reduces_probe_rows():
    """TPC-DS-shaped star join: a selective dimension must shrink the
    fact-side shuffle via the map-side bloom filter, with results exactly
    matching pandas (VERDICT r2 #4 done-criteria)."""
    from spark_rapids_tpu.ops import bloom as B
    rng = np.random.default_rng(11)
    fact, dim = _star_shapes(rng)
    sess = srt.session(**{"spark.rapids.sql.autoBroadcastJoinThreshold": -1})
    f = sess.create_dataframe(fact, num_partitions=4)
    d = sess.create_dataframe(dim, num_partitions=2)
    built0 = B.STATS["blooms_built"]
    in0, kept0 = B.STATS["probe_rows_in"], B.STATS["probe_rows_kept"]
    got = f.join(d, f.fk == d.pk, "inner").collect().to_pandas()
    exp = fact.to_pandas().merge(dim.to_pandas(), left_on="fk",
                                 right_on="pk", how="inner")
    assert len(got) == len(exp)
    assert abs(got["x"].sum() - exp["x"].sum()) < 1e-6
    assert B.STATS["blooms_built"] > built0
    rows_in = B.STATS["probe_rows_in"] - in0
    rows_kept = B.STATS["probe_rows_kept"] - kept0
    assert rows_in >= 300_000
    assert rows_kept < rows_in * 0.1, \
        f"bloom kept {rows_kept}/{rows_in} — no real reduction"


def test_bloom_left_semi_correct():
    from spark_rapids_tpu.ops import bloom as B
    rng = np.random.default_rng(12)
    fact, dim = _star_shapes(rng, n_fact=100_000, n_dim=200)
    sess = srt.session(**{"spark.rapids.sql.autoBroadcastJoinThreshold": -1})
    f = sess.create_dataframe(fact, num_partitions=3)
    d = sess.create_dataframe(dim, num_partitions=2)
    built0 = B.STATS["blooms_built"]
    got = f.join(d, f.fk == d.pk, "left_semi").collect().to_pandas()
    exp = fact.to_pandas()[fact.to_pandas().fk.isin(dim.to_pandas().pk)]
    assert len(got) == len(exp)
    assert abs(got["x"].sum() - exp["x"].sum()) < 1e-6
    assert B.STATS["blooms_built"] > built0


def test_bloom_not_used_for_outer_joins():
    """Left outer joins must emit unmatched probe rows — exactly the rows
    the bloom filter would drop; it must not engage."""
    from spark_rapids_tpu.ops import bloom as B
    rng = np.random.default_rng(13)
    fact, dim = _star_shapes(rng, n_fact=50_000, n_dim=100)
    sess = srt.session(**{"spark.rapids.sql.autoBroadcastJoinThreshold": -1})
    f = sess.create_dataframe(fact, num_partitions=3)
    d = sess.create_dataframe(dim, num_partitions=2)
    built0 = B.STATS["blooms_built"]
    got = f.join(d, f.fk == d.pk, "left").collect().to_pandas()
    assert B.STATS["blooms_built"] == built0
    exp = fact.to_pandas().merge(dim.to_pandas(), left_on="fk",
                                 right_on="pk", how="left")
    assert len(got) == len(exp)


def test_bloom_kill_switch():
    from spark_rapids_tpu.ops import bloom as B
    rng = np.random.default_rng(14)
    fact, dim = _star_shapes(rng, n_fact=50_000, n_dim=100)
    sess = srt.session(**{
        "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.sql.join.bloomFilter.enabled": False})
    f = sess.create_dataframe(fact, num_partitions=3)
    d = sess.create_dataframe(dim, num_partitions=2)
    built0 = B.STATS["blooms_built"]
    got = f.join(d, f.fk == d.pk, "inner").collect()
    assert B.STATS["blooms_built"] == built0
    exp = fact.to_pandas().merge(dim.to_pandas(), left_on="fk",
                                 right_on="pk", how="inner")
    assert len(got) == len(exp)


def test_bloom_not_used_multi_slice():
    """In a multi-slice topology the build exchange materializes only the
    slice-LOCAL reduce partitions, so a bloom built from it would cover a
    subset of build rows and its map-side probe filter would drop rows
    whose matches live in peer-owned partitions (false negatives — the
    one thing a bloom join must never do).  The bloom must not engage
    (advisor r3 high finding)."""
    from spark_rapids_tpu.ops import bloom as B
    rng = np.random.default_rng(15)
    fact, dim = _star_shapes(rng, n_fact=50_000, n_dim=100)
    sess = srt.session(**{
        "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
        "spark.rapids.shuffle.topology.numSlices": 2,
        "spark.rapids.shuffle.topology.sliceId": 0,
        "spark.sql.adaptive.enabled": False})
    try:
        f = sess.create_dataframe(fact, num_partitions=4)
        d = sess.create_dataframe(dim, num_partitions=2)
        built0 = B.STATS["blooms_built"]
        got = f.join(d, f.fk == d.pk, "inner").collect().to_pandas()
        assert B.STATS["blooms_built"] == built0
        # this slice returns its local partitions only — a strict subset,
        # every row of which must match the oracle
        exp = fact.to_pandas().merge(dim.to_pandas(), left_on="fk",
                                     right_on="pk", how="inner")
        assert 0 < len(got) < len(exp)
        exp_keys = exp.groupby("fk").size()
        for fk, cnt in got.groupby("fk").size().items():
            assert exp_keys[fk] == cnt
    finally:
        srt.session(**{"spark.rapids.shuffle.topology.numSlices": 1,
                       "spark.sql.adaptive.enabled": True,
                       "spark.rapids.sql.autoBroadcastJoinThreshold":
                           10 * 1024 * 1024})


def test_broadcast_hint_forces_broadcast(sess):
    """F.broadcast(dim) / dim.hint('broadcast') skip the size threshold
    (Spark's ResolveHints + JoinSelection)."""
    rng = np.random.default_rng(21)
    fact = pa.table({"fk": rng.integers(0, 500, 20_000),
                     "x": rng.random(20_000)})
    dim = pa.table({"pk": np.arange(500, dtype=np.int64),
                    "n": [f"d{i}" for i in range(500)]})
    sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold", 1)
    try:
        f = sess.create_dataframe(fact, num_partitions=3)
        d = sess.create_dataframe(dim, num_partitions=2)
        q = f.join(F.broadcast(d), f.fk == d.pk, "inner")
        rep = str(sess.physical_plan(q).tree_string())
        assert "BroadcastHashJoin" in rep
        got = q.count()
        exp = fact.to_pandas().merge(dim.to_pandas(), left_on="fk",
                                     right_on="pk").shape[0]
        assert got == exp
        # unhinted stays off the broadcast path under the tiny threshold
        rep2 = str(sess.physical_plan(
            f.join(d, f.fk == d.pk, "inner")).tree_string())
        assert "BroadcastHashJoin" not in rep2
        # hint() surface, and unknown hints are ignored like Spark
        assert "BroadcastHashJoin" in str(sess.physical_plan(
            f.join(d.hint("broadcast"), f.fk == d.pk, "left")).tree_string())
        assert d.hint("nosuchhint") is d
    finally:
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      10 * 1024 * 1024)


def test_broadcast_hint_survives_transformations(sess):
    """select/filter/rename after the hint keep it (Spark's ResolvedHint
    survives transformations)."""
    rng = np.random.default_rng(22)
    fact = pa.table({"fk": rng.integers(0, 100, 5_000)})
    dim = pa.table({"pk": np.arange(100, dtype=np.int64),
                    "n": [f"d{i}" for i in range(100)]})
    sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold", 1)
    try:
        f = sess.create_dataframe(fact, num_partitions=3)
        d = F.broadcast(sess.create_dataframe(dim, num_partitions=2))
        d2 = d.filter(d.pk >= 0).withColumnRenamed("n", "name")
        q = f.join(d2, f.fk == d2.pk, "inner")
        assert "BroadcastHashJoin" in str(sess.physical_plan(q).tree_string())
        assert q.count() == 5_000
    finally:
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      10 * 1024 * 1024)


def test_broadcast_hint_scoping(sess):
    """A hint consumed by an inner join must not escape and broadcast the
    whole join result; a LEFT-side hint is honored for inner
    expression joins with output order preserved."""
    rng = np.random.default_rng(23)
    fact = sess.create_dataframe(
        pa.table({"fk": rng.integers(0, 50, 5_000)}), num_partitions=3)
    fact2 = sess.create_dataframe(
        pa.table({"gk": rng.integers(0, 50, 5_000)}), num_partitions=3)
    dim = sess.create_dataframe(
        pa.table({"pk": np.arange(50, dtype=np.int64),
                  "n": [f"d{i}" for i in range(50)]}))
    sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold", 1)
    try:
        mid = fact2.join(F.broadcast(dim), fact2.gk == dim.pk, "inner")
        rep = str(sess.physical_plan(
            fact.join(mid, fact.fk == mid.gk, "inner")).tree_string())
        assert rep.count("BroadcastExchange") <= 1, rep
        q = F.broadcast(dim).join(fact, dim.pk == fact.fk, "inner")
        assert "BroadcastHashJoin" in str(sess.physical_plan(q)
                                          .tree_string())
        out = q.collect()
        assert out.column_names == ["pk", "n", "fk"]
        assert out.num_rows == 5_000
    finally:
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      10 * 1024 * 1024)
