"""Compile-cache + whole-stage-fusion behavior (VERDICT round-1 items 2-3):
repeated collect() of the same query must reuse compiled kernels instead of
re-tracing, and fused plans must match unfused results exactly."""

import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.config import FUSION_ENABLED
from spark_rapids_tpu.sql.physical.kernel_cache import (cache_stats,
                                                        clear_cache)


def _q1_like(sess, rows=50_000):
    rng = np.random.default_rng(7)
    df = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 5, rows).astype(np.int64),
        "v": rng.random(rows).astype(np.float32),
        "w": rng.random(rows).astype(np.float32),
    }))
    return (df.filter(df.v < 0.8)
            .withColumn("x", df.v * (1.0 - df.w))
            .groupBy("k")
            .agg(F.sum(F.col("x")).alias("sx"),
                 F.avg(F.col("v")).alias("av"),
                 F.count("*").alias("c"))
            .orderBy("k"))


def test_repeat_collect_hits_cache(session):
    clear_cache()  # order-independent: force a genuinely cold first run
    q = _q1_like(session)
    t0 = time.perf_counter()
    first = q.collect()
    cold = time.perf_counter() - t0
    misses_after_first = cache_stats()["misses"]

    second = q.collect()
    stats = cache_stats()
    # the second run may compile exactly ONE new program: the speculative
    # fused group+reduce sized to the group count the first run observed
    assert stats["misses"] - misses_after_first <= 1, \
        "second collect() compiled new kernels instead of reusing cached ones"
    misses_after_second = stats["misses"]

    t0 = time.perf_counter()
    third = q.collect()
    warm = time.perf_counter() - t0
    stats = cache_stats()
    assert stats["misses"] == misses_after_second, \
        "steady-state collect() must be fully cached"
    assert stats["hits"] > 0
    assert first.to_pylist() == second.to_pylist() == third.to_pylist()
    # compile amortization: warm run must be dramatically faster
    assert warm * 20 < cold, f"cold={cold:.3f}s warm={warm:.3f}s"


def test_fresh_plan_same_query_reuses_kernels(session):
    """A *newly built* identical query (new expression objects) must reuse
    the same compiled kernels — keys are structural, not object-identity."""
    _q1_like(session).collect()
    misses = cache_stats()["misses"]
    _q1_like(session).collect()
    assert cache_stats()["misses"] == misses


def test_fused_kernel_not_leaked_to_unfused_query(session):
    """Regression: a fused partial kernel (filter absorbed) must not be
    served to a later UNFUSED aggregate with the same grouping/slots —
    the pre-step chain is part of the cache key and baked into the
    closure, never read from mutable exec state."""
    df = session.create_dataframe(pa.table({
        "k": [0, 0, 1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]}))
    fused = (df.filter(df.v < 4.0).groupBy("k")
             .agg(F.sum(F.col("v")).alias("sv")).orderBy("k"))
    assert [(r["k"], r["sv"]) for r in fused.collect().to_pylist()] == \
        [(0, 3.0), (1, 3.0)]
    unfused = (df.groupBy("k").agg(F.sum(F.col("v")).alias("sv"))
               .orderBy("k"))
    assert [(r["k"], r["sv"]) for r in unfused.collect().to_pylist()] == \
        [(0, 3.0), (1, 7.0), (2, 11.0)]


def test_batched_2d_reduce_matches_per_slot(session, monkeypatch):
    """The TPU-only batched segmented-reduce path must agree with the
    per-slot path (it has no CPU coverage otherwise)."""
    import spark_rapids_tpu.sql.physical.aggregate as agg_mod
    rng = np.random.default_rng(11)
    df = session.create_dataframe(pa.table({
        "k": rng.integers(0, 7, 5000).astype(np.int64),
        "v": rng.random(5000).astype(np.float32),
        "i": rng.integers(-50, 50, 5000).astype(np.int64),
    }))
    q = (df.groupBy("k")
         .agg(F.sum(F.col("v")).alias("sv"), F.min(F.col("i")).alias("mi"),
              F.max(F.col("i")).alias("ma"), F.count("*").alias("c"),
              F.avg(F.col("v")).alias("av"))
         .orderBy("k"))
    base = q.collect().to_pylist()
    monkeypatch.setattr(agg_mod, "_use_batched_reduce",
                        lambda xp: xp.__name__ != "numpy")
    clear_cache()  # drop kernels traced through the per-slot path
    try:
        batched = q.collect().to_pylist()
    finally:
        clear_cache()  # don't leak batched-trace kernels to other tests
    assert batched == base


def test_fusion_matches_unfused(session):
    q = _q1_like(session)
    fused = q.collect()
    session.conf.set(FUSION_ENABLED.key, False)
    try:
        unfused = q.collect()
    finally:
        session.conf.set(FUSION_ENABLED.key, True)
    assert fused.to_pylist() == unfused.to_pylist()


def test_fused_stage_in_plan(session):
    rng = np.random.default_rng(3)
    df = session.create_dataframe(pa.table({
        "a": rng.integers(0, 9, 100).astype(np.int64),
        "b": rng.random(100),
    }))
    q = (df.filter(df.a > 2)
         .withColumn("c", df.b * 2.0)
         .filter(df.b < 0.9)
         .select("a", "c"))
    plan = session.physical_plan(q)
    assert "FusedStage" in plan.tree_string()
    out = q.collect()
    expect = [(int(a), float(b) * 2.0)
              for a, b in zip(np.asarray(df._plan.table["a"]),
                              np.asarray(df._plan.table["b"]))
              if a > 2 and b < 0.9]
    got = [(r["a"], r["c"]) for r in out.to_pylist()]
    assert got == pytest.approx(expect)
