"""Query lifecycle resilience (serving/lifecycle.py, docs/robustness.md):

* the cancellation RACE MATRIX — a cancel fired at every lifecycle poll
  site (before admission, during the semaphore wait, mid-partition,
  during prefetch, during spill I/O, during shuffle fetch) x parallelism
  {1, 4} must surface a typed QueryCancelled with ZERO leaked semaphore
  permits, retention pins, or spill-catalog handles;
* per-query deadlines (QueryDeadlineExceeded, enforcement accuracy);
* the WFQ virtual-finish-time rollback on admission timeout/cancel (a
  tenant timing out repeatedly must not tax its future share);
* pressure-aware plan degradation (PressureSignal + kill switch);
* the poison-query quarantine + degraded-engine probe protocol;
* fatal-dump identity stamps (tenant/session/query + doctor verdict).
"""

import gc
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import retention
from spark_rapids_tpu.memory.fatal import FatalDeviceError
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spill import BufferCatalog
from spark_rapids_tpu.serving import ServingEngine, lifecycle as lc
from spark_rapids_tpu.serving.admission import AdmissionController
from spark_rapids_tpu.sql import functions as F


@pytest.fixture(autouse=True)
def _clean_lifecycle():
    """Every test starts and ends with no live query contexts, no cancel
    trigger, and a known semaphore width."""
    lc.set_cancel_trigger(None)
    yield
    lc.set_cancel_trigger(None)
    assert not lc.live_queries(), "test leaked a registered QueryContext"
    TpuSemaphore.shutdown()


def _tables(rows=6000):
    rng = np.random.default_rng(7)
    fact = pa.table({"k": rng.integers(0, 50, rows),
                     "q": rng.integers(0, 100, rows),
                     "v": rng.random(rows)})
    dim = pa.table({"k": np.arange(50, dtype=np.int64),
                    "w": rng.random(50)})
    return fact, dim


def _query(sess, fact, dim):
    f = sess.create_dataframe(fact, num_partitions=4)
    d = sess.create_dataframe(dim, num_partitions=2)
    return (f.join(d, on="k", how="inner")
            .groupBy("k").agg(F.count("*").alias("n"),
                              F.sum(f.v).alias("sv"))
            .orderBy("k").collect())


# --------------------------------------------------------------------------
# cancellation race matrix
# --------------------------------------------------------------------------

#: (site, extra conf) — each leg fires the cancel at a DIFFERENT
#: chokepoint; the conf routes the query through that chokepoint
_SITE_CONF = {
    "partition": {},
    "sem_wait": {},
    "prefetch": {"spark.rapids.tpu.prefetch.enabled": True,
                 "spark.rapids.tpu.prefetch.depth": 2},
    "shuffle": {"spark.rapids.shuffle.localDeviceResident.enabled": False,
                "spark.rapids.shuffle.compression.codec": "none",
                "spark.rapids.sql.autoBroadcastJoinThreshold": 1},
    "exchange": {"spark.rapids.sql.autoBroadcastJoinThreshold": 1},
    # fusion off so the collect tail stays an explicit DeviceToHostExec
    # (the fused-collect fetch path has no stager)
    "stager": {"spark.rapids.tpu.transfer.doubleBuffer.enabled": True,
               "spark.rapids.tpu.sql.fusion.enabled": False},
}


@pytest.mark.parametrize("parallelism", [1, 4])
@pytest.mark.parametrize("site", sorted(_SITE_CONF))
def test_cancel_race_matrix(site, parallelism, tmp_path):
    """A cancel landing at ``site`` surfaces QueryCancelled and every
    accounting — semaphore permits, retention pins, catalog handles —
    returns to its pre-query baseline."""
    fact, dim = _tables()
    conf = {"spark.rapids.tpu.task.parallelism": parallelism,
            "spark.rapids.memory.spillDir": str(tmp_path)}
    conf.update(_SITE_CONF[site])
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.memory.spillDir": str(tmp_path)}))
    sess = srt.session(**conf)
    # two clean runs first: the first warms the upload cache (whose
    # pins are a LEGIT steady-state baseline), the delta between them is
    # the per-query steady-state growth (deferred shuffle cleanup holds
    # handles until its TTL sweep) — a cancelled query may grow by AT
    # MOST the same amount
    expected = _query(sess, fact, dim)
    gc.collect()
    h1 = len(BufferCatalog.get().leak_report())
    assert _query(sess, fact, dim).equals(expected)
    gc.collect()
    pins0 = retention.pinned_count()
    h2 = len(BufferCatalog.get().leak_report())
    clean_growth = h2 - h1

    lc.set_cancel_trigger(site)
    with pytest.raises(lc.QueryCancelled):
        _query(sess, fact, dim)
    assert sess.last_cancel_latency_ms is not None

    assert TpuSemaphore.get().active_tasks() == 0, site
    gc.collect()  # GC-reaped pins (batches dropped by the unwind)
    assert retention.pinned_count() <= pins0, (
        site, retention.pinned_count(), pins0)
    assert len(BufferCatalog.get().leak_report()) <= h2 + clean_growth, (
        site, BufferCatalog.get().leak_report())
    assert not lc.live_queries()
    # and the session still works afterwards, bit-identically
    lc.set_cancel_trigger(None)
    assert _query(sess, fact, dim).equals(expected)


@pytest.mark.parametrize("parallelism", [1, 4])
def test_cancel_race_during_spill(parallelism, tmp_path):
    """Cancel fired inside the spill disk-I/O chokepoint: injected
    RetryOOMs force spill_all_device, the 1-byte host budget overflows
    straight to the disk tier (the chaos-soak recipe), and a cancel
    landing in that I/O drains cleanly."""
    from spark_rapids_tpu.robustness import faults
    fact, _ = _tables(8000)
    BufferCatalog.reset(RapidsConf({
        "spark.rapids.memory.host.spillStorageSize": 1,
        "spark.rapids.memory.spillDir": str(tmp_path)}))
    sess = srt.session(**{
        "spark.rapids.tpu.task.parallelism": parallelism,
        "spark.rapids.sql.sort.outOfCore.targetRows": 512,
        "spark.rapids.memory.spillDir": str(tmp_path)})

    def q():
        df = sess.create_dataframe(fact, num_partitions=4)
        return df.orderBy(df.v.desc_nulls_first(), "k") \
            .select("k", "v").collect()
    # seed 0 @ p=0.7 injects at ordinals 0/1/2 and skips 3 (verified by
    # the pure _decision schedule): the first query spills for sure and
    # with_retry never exhausts its retry budget
    faults.arm_chaos(seed=0, sites="memory.oom.retry:0.7")
    try:
        q()  # proves the shape actually traverses the spill site
        assert BufferCatalog.get().disk_bytes >= 0
        assert BufferCatalog.get().spill_count > 0, \
            "recipe no longer exercises the spill tier"
        lc.set_cancel_trigger("spill")
        with pytest.raises(lc.QueryCancelled):
            q()
    finally:
        faults.disarm_chaos()
    assert TpuSemaphore.get().active_tasks() == 0
    BufferCatalog.reset()


@pytest.mark.parametrize("parallelism", [1, 4])
def test_cancel_before_admission(parallelism):
    """A query cancelled while still WAITING for admission leaves the
    queue with QueryCancelled, never consumes a slot, and rolls its
    tenant's WFQ vft back."""
    eng = ServingEngine(**{
        "spark.rapids.tpu.serving.maxConcurrentQueries": 1,
        "spark.rapids.tpu.task.parallelism": parallelism})
    try:
        fact, dim = _tables(2000)
        blocker = eng.admission.acquire("blocker")
        sess = eng.session(tenant="victim")
        errs = {}

        def submit():
            try:
                _query(sess, fact, dim)
            except BaseException as e:  # noqa: BLE001
                errs["e"] = e

        th = threading.Thread(target=submit)
        th.start()
        deadline = time.monotonic() + 10
        while eng.admission.snapshot()["queued"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        vft_waiting = eng.admission._tenant_vft["victim"]
        assert eng.cancel_tenant("victim") == 1
        th.join(20)
        assert isinstance(errs.get("e"), lc.QueryCancelled), errs
        # slot never consumed; vft rolled back below the waiting value
        snap = eng.admission.snapshot()
        assert snap["queued"] == 0
        assert snap["per_tenant"].get("victim", {}).get(
            "in_flight", 0) == 0
        assert eng.admission._tenant_vft["victim"] < vft_waiting
        eng.admission.release(blocker)
    finally:
        eng.close()


def test_deadline_exceeded_typed_and_bounded():
    fact, _ = _tables(60_000)
    sess = srt.session(**{"spark.rapids.tpu.query.deadlineMs": 1})
    df = sess.create_dataframe(fact, num_partitions=8)
    t0 = time.perf_counter()
    with pytest.raises(lc.QueryDeadlineExceeded):
        df.groupBy("k").agg(F.sum(F.col("v")).alias("s")) \
            .orderBy("k").collect()
    # enforcement is cooperative: bounded by poll interval + one device
    # dispatch, which on XLA:CPU includes a compile — generous bound
    assert time.perf_counter() - t0 < 30
    assert TpuSemaphore.get().active_tasks() == 0
    assert not lc.live_queries()


def test_poll_sites_conf_restricts_checks():
    """pollSites=shuffle means the partition site never raises — the
    trigger at `partition` goes unobserved and the query completes."""
    fact, dim = _tables(2000)
    sess = srt.session(**{
        "spark.rapids.tpu.query.cancel.pollSites": "shuffle"})
    lc.set_cancel_trigger("partition")
    got = _query(sess, fact, dim)  # trigger only fires at polled sites
    assert got.num_rows == 50


def test_chaos_cancel_race_site_types_errors():
    """query.cancel.race armed at p=1: the query dies with the TYPED
    QueryCancelled (never a hang / secondary error) and accounting is
    clean."""
    from spark_rapids_tpu.robustness import faults
    fact, dim = _tables(3000)
    sess = srt.session(**{"spark.rapids.tpu.task.parallelism": 4})
    _query(sess, fact, dim)
    faults.arm_chaos(seed=3, sites="query.cancel.race:1.0")
    try:
        with pytest.raises(lc.QueryCancelled):
            _query(sess, fact, dim)
    finally:
        faults.disarm_chaos()
    assert TpuSemaphore.get().active_tasks() == 0
    assert not lc.live_queries()


# --------------------------------------------------------------------------
# WFQ vft rollback (satellite)
# --------------------------------------------------------------------------

def test_admission_timeout_rolls_back_vft():
    """Two tenants, one timing out repeatedly: the timeouts must not
    advance the loser's virtual clock — its eventual real acquire gets
    the same share a fresh tenant would."""
    ctrl = AdmissionController(max_concurrent=1, timeout_ms=0)
    blocker = ctrl.acquire("steady")
    with pytest.raises(Exception):
        ctrl.acquire("flaky", timeout_ms=10)
    vft1 = ctrl._tenant_vft.get("flaky", 0.0)
    for _ in range(4):
        with pytest.raises(Exception):
            ctrl.acquire("flaky", timeout_ms=10)
    # rollback is exact: repeated abandoned waits do not ACCUMULATE —
    # the vft after five timeouts equals the vft after one
    assert ctrl._tenant_vft.get("flaky", 0.0) == pytest.approx(vft1)
    ctrl.release(blocker)
    # and the tenant is not starved when it finally asks for real
    t = ctrl.acquire("flaky", timeout_ms=2000)
    ctrl.release(t)
    assert ctrl.stats["timeouts"] == 5


def test_admission_timeout_vft_vs_unpenalized_tenant():
    """End-to-end fairness check: after N timeouts, flaky's next vft is
    NOT N/weight ahead of a tenant that never timed out."""
    ctrl = AdmissionController(max_concurrent=1, timeout_ms=0)
    blocker = ctrl.acquire("steady")
    for _ in range(8):
        with pytest.raises(Exception):
            ctrl.acquire("flaky", timeout_ms=5)
    ctrl.release(blocker)
    a = ctrl.acquire("flaky")
    ctrl.release(a)
    b = ctrl.acquire("fresh")
    ctrl.release(b)
    # both grants happened at adjacent vclock positions: |vft diff| <= 1
    assert abs(ctrl._tenant_vft["flaky"]
               - ctrl._tenant_vft["fresh"]) <= 1.0 + 1e-9


# --------------------------------------------------------------------------
# pressure-aware degradation
# --------------------------------------------------------------------------

def test_pressure_signal_kill_switch_and_thresholds():
    conf = RapidsConf({"spark.rapids.tpu.serving.pressure.enabled": False})
    ctrl = AdmissionController(max_concurrent=1)
    sig = lc.PressureSignal(conf)
    assert sig.plan_overrides(ctrl, conf) == {}

    conf_on = RapidsConf({
        "spark.rapids.tpu.serving.pressure.enabled": True,
        "spark.rapids.tpu.serving.pressure.queueDepth": 2,
        "spark.rapids.sql.concurrentGpuTasks": 4,
        "spark.rapids.sql.batchSizeRows": 1 << 20})
    sig = lc.PressureSignal(conf_on)
    assert sig.plan_overrides(ctrl, conf_on) == {}  # calm queue
    # saturate: one runner + 2 queued waiters -> depth threshold
    blocker = ctrl.acquire("a")
    waiters = []

    def w():
        t = ctrl.acquire("b")
        ctrl.release(t)
    ths = [threading.Thread(target=w) for _ in range(2)]
    for t in ths:
        t.start()
    deadline = time.monotonic() + 10
    while ctrl.snapshot()["queued"] < 2:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    over = sig.plan_overrides(ctrl, conf_on)
    assert over["spark.rapids.sql.concurrentGpuTasks"] == 2
    assert over["spark.rapids.sql.batchSizeRows"] == 1 << 18
    assert over[
        "spark.rapids.sql.join.speculativeSizing.enabled"] is False
    ctrl.release(blocker)
    for t in ths:
        t.join(20)


def test_pressure_degraded_plan_bit_identical():
    """A degraded plan (chaos admission.pressure forces the signal)
    returns bit-identical results and stamps pressureDegraded."""
    from spark_rapids_tpu.robustness import faults
    fact, dim = _tables(4000)
    clean_eng = ServingEngine()
    try:
        expected = _query(clean_eng.session(tenant="t"), fact, dim)
    finally:
        clean_eng.close()
    eng = ServingEngine(**{
        "spark.rapids.tpu.serving.pressure.enabled": True})
    try:
        sess = eng.session(tenant="t")
        faults.arm_chaos(seed=5, sites="admission.pressure:1.0")
        try:
            got = _query(sess, fact, dim)
        finally:
            faults.disarm_chaos()
        assert got.equals(expected)
        assert sess.last_query_metrics.get("pressureDegraded") == 1
    finally:
        eng.close()


# --------------------------------------------------------------------------
# poison-query quarantine + degraded-engine probe
# --------------------------------------------------------------------------

def test_fatal_quarantines_fingerprint_and_probe_recovers():
    from spark_rapids_tpu.robustness import faults
    fact, dim = _tables(3000)
    eng = ServingEngine()
    try:
        s0 = eng.session(tenant="t0")
        s1 = eng.session(tenant="t1")
        expected = _query(s1, fact, dim)
        faults.arm_chaos(seed=1, sites="device.fatal:1.0")
        try:
            with pytest.raises(FatalDeviceError):
                _query(s0, fact, dim)
        finally:
            faults.disarm_chaos()
        assert eng.is_degraded()
        assert eng.quarantine.size() == 1
        # immediate same-plan retry: the (healthy-device) probe clears
        # the degraded mark, but the fingerprint stays quarantined
        with pytest.raises(lc.QueryQuarantined):
            _query(s0, fact, dim)
        assert not eng.is_degraded()
        # the sibling tenant's DIFFERENT plan runs, bit-identical
        f = s1.create_dataframe(fact, num_partitions=4)
        assert f.groupBy("q").agg(F.sum(f.v).alias("s")) \
            .orderBy("q").collect().num_rows > 0
        # quarantine expires by TTL (expiry is stamped at add time —
        # rewind the live entries rather than waiting out the 60s TTL)
        with eng.quarantine._lock:
            for fp in list(eng.quarantine._entries):
                eng.quarantine._entries[fp] = time.monotonic() - 1
        assert eng.quarantine.size() == 0
        assert _query(s1, fact, dim).equals(expected)
    finally:
        eng.close()


def test_degraded_engine_refuses_until_probe_interval():
    eng = ServingEngine(**{
        "spark.rapids.tpu.serving.degraded.probeIntervalMs": 60_000})
    try:
        eng.note_fatal(RuntimeError("boom"), "fp123", tenant="t")
        assert eng.is_degraded()
        # first probe attempt is immediate and (healthy device) recovers
        eng.check_admittable("other")
        assert not eng.is_degraded()
        # re-degrade and exhaust the immediate probe with a failure:
        # subsequent admissions are refused until the interval passes
        eng.note_fatal(RuntimeError("boom2"), "fp456", tenant="t")
        eng._next_probe = time.monotonic() + 60
        with pytest.raises(lc.EngineDegraded):
            eng.check_admittable("")
    finally:
        eng.close()


def test_quarantine_registry_ttl_and_bound():
    reg = lc.QuarantineRegistry(ttl_ms=50, max_entries=3)
    for i in range(5):
        reg.add(f"fp{i}")
    assert reg.size() == 3  # oldest evicted past the bound
    assert reg.quarantined("fp4")
    assert not reg.quarantined("fp0")
    time.sleep(0.08)
    assert reg.size() == 0
    assert not reg.quarantined("fp4")


# --------------------------------------------------------------------------
# fatal dump identity stamps (satellite)
# --------------------------------------------------------------------------

def test_fatal_dump_stamps_identity_and_doctor_verdict(tmp_path):
    from spark_rapids_tpu.memory.fatal import handle_fatal
    from spark_rapids_tpu.observability import doctor
    from spark_rapids_tpu.sql.physical.base import TaskContext
    doctor.LAST_VERDICT = {"verdict": "sync-bound",
                           "at": time.monotonic()}
    conf = RapidsConf({
        "spark.rapids.tpu.fatalDump.path": str(tmp_path),
        "spark.rapids.tpu.serving.tenant": "acme"})
    qctx = lc.QueryContext(7, session_id="sess-test-1", tenant="acme")
    lc.register(qctx)
    try:
        with lc.installed(qctx):
            tctx = TaskContext(3, conf)
            with tctx.as_current():
                err = handle_fatal(RuntimeError("XlaRuntimeError: boom"),
                                   conf=conf)
    finally:
        lc.unregister(qctx)
    assert err.dump_path
    with open(err.dump_path) as fh:
        dump = fh.read()
    assert "tenant=acme" in dump
    assert "session=sess-test-1" in dump
    assert "query=7" in dump
    assert "partition=3" in dump
    assert "last doctor verdict: sync-bound" in dump


# --------------------------------------------------------------------------
# tenant-aware spill ordering
# --------------------------------------------------------------------------

def test_tenant_aware_spill_evicts_over_budget_first(tmp_path):
    from spark_rapids_tpu.columnar.convert import arrow_to_device
    from spark_rapids_tpu.sql.physical.base import TaskContext
    cat = BufferCatalog.reset(RapidsConf(
        {"spark.rapids.memory.spillDir": str(tmp_path)}))
    cat.set_tenant_budgets({"hog": 1}, 0)  # 1 byte: hog is over budget

    def batch():
        return arrow_to_device(
            pa.table({"x": np.arange(1024, dtype=np.int64)}))

    def add_as(tenant):
        conf = RapidsConf(
            {"spark.rapids.tpu.serving.tenant": tenant})
        with TaskContext(0, conf).as_current():
            return cat.add_batch(batch())

    h_meek = add_as("meek")      # registered FIRST (lowest seq)
    h_hog = add_as("hog")
    # without tenant awareness, meek (older seq) would spill first;
    # with it, the over-budget hog's buffer goes first
    cat.synchronous_spill(cat.device_bytes - 1)
    assert cat.tier_of(h_hog) != "device"
    assert cat.tier_of(h_meek) == "device"
    BufferCatalog.reset()


# --------------------------------------------------------------------------
# misc lifecycle mechanics
# --------------------------------------------------------------------------

def test_cancel_is_idempotent_and_registry_scoped():
    q1 = lc.QueryContext(1, session_id="sA")
    q2 = lc.QueryContext(2, session_id="sB", tenant="tb")
    lc.register(q1)
    lc.register(q2)
    try:
        assert lc.LIFECYCLE["on"]
        assert lc.cancel_session("sA") == 1
        assert lc.cancel_session("sA") == 0      # idempotent
        assert not q2.cancelled
        assert lc.cancel_tenant("tb") == 1
        assert q2.cancelled
    finally:
        lc.unregister(q1)
        lc.unregister(q2)
    assert not lc.LIFECYCLE["on"]


def test_cancel_at_mesh_poll_site_zero_leak():
    """ISSUE 19 satellite: a cancel landing at the ``mesh`` poll site —
    polled at the top of ``mesh_shuffle_batches``, BEFORE any device
    check or collective dispatch — must surface QueryCancelled with zero
    leaked pins or permits (the exchange is abandoned before the plane
    acquires anything)."""
    from spark_rapids_tpu.parallel import mesh as M
    assert "mesh" in lc.POLL_SITES
    pins0 = retention.pinned_count()
    q = lc.QueryContext(91, session_id="sMesh")
    lc.register(q)
    try:
        with lc.installed(q):
            lc.set_cancel_trigger("mesh")
            with pytest.raises(lc.QueryCancelled) as ei:
                M.mesh_shuffle_batches(None, [], [], 0)
            assert "mesh" in str(ei.value)
            # not the degrade path: a cancel must FAIL the query, never
            # silently fall back to the local shuffle plane
            assert not isinstance(ei.value, M.MeshShuffleUnsupported)
    finally:
        lc.set_cancel_trigger(None)
        lc.unregister(q)
    assert retention.pinned_count() == pins0
    assert TpuSemaphore.get().active_tasks() == 0
    assert not lc.live_queries()


def test_cancellable_sleep_bounded():
    q = lc.QueryContext(1, session_id="sC")
    lc.register(q)
    try:
        with lc.installed(q):
            threading.Timer(0.05, q.cancel).start()
            t0 = time.perf_counter()
            with pytest.raises(lc.QueryCancelled):
                lc.cancellable_sleep(5.0, "shuffle")
            assert time.perf_counter() - t0 < 1.0
    finally:
        lc.unregister(q)
