"""Memory runtime tests — spill tiers, retry framework, semaphore, task
completion (reference suites: RapidsDiskStoreSuite, RapidsHostMemoryStoreSuite,
WithRetrySuite, GpuSortRetrySuite; SURVEY §4 tier 2)."""
import os

import threading
import time

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import make_fixed_column
from spark_rapids_tpu.config import (HOST_SPILL_STORAGE_SIZE, RapidsConf,
                                     SPILL_DIR, TEST_INJECT_RETRY_OOM,
                                     TEST_INJECT_SPLIT_OOM)
from spark_rapids_tpu.memory import (BufferCatalog, DeviceManager, RetryOOM,
                                     ScalableTaskCompletion,
                                     SpillableColumnarBatch,
                                     SplitAndRetryOOM, TpuSemaphore,
                                     arm_oom_injection, batch_device_bytes,
                                     split_spillable_in_half, with_retry,
                                     with_retry_no_split)


def make_batch(n=100, seed=0):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    from spark_rapids_tpu.columnar.column import bucket_capacity
    cap = bucket_capacity(n)
    a = np.zeros(cap, dtype=np.int64)
    a[:n] = rng.integers(0, 1000, n)
    b = np.zeros(cap, dtype=np.float64)
    b[:n] = rng.random(n)
    cols = (make_fixed_column(T.LONG, jnp.asarray(a)),
            make_fixed_column(T.DOUBLE, jnp.asarray(b)))
    return ColumnarBatch.make(("a", "b"), cols, n)


def batches_equal(x: ColumnarBatch, y: ColumnarBatch) -> bool:
    if x.num_rows_int != y.num_rows_int:
        return False
    n = x.num_rows_int
    for cx, cy in zip(x.columns, y.columns):
        if not np.array_equal(np.asarray(cx.data)[:n], np.asarray(cy.data)[:n]):
            return False
        if not np.array_equal(np.asarray(cx.validity)[:n],
                              np.asarray(cy.validity)[:n]):
            return False
    return True


@pytest.fixture()
def catalog(tmp_path):
    conf = RapidsConf({SPILL_DIR.key: str(tmp_path)})
    cat = BufferCatalog.reset(conf)
    yield cat
    cat.close_all()
    BufferCatalog.reset()


class TestSpillFramework:
    def test_roundtrip_device(self, catalog):
        b = make_batch(50)
        h = catalog.add_batch(b)
        assert catalog.tier_of(h) == "device"
        assert batches_equal(catalog.get_batch(h), b)
        catalog.remove(h)
        assert catalog.device_bytes == 0

    def test_spill_to_host_and_unspill(self, catalog):
        b = make_batch(200)
        h = catalog.add_batch(b)
        spilled = catalog.synchronous_spill(0)
        assert spilled > 0
        assert catalog.tier_of(h) == "host"
        assert catalog.device_bytes == 0
        got = catalog.get_batch(h)           # unspill back to device
        assert catalog.tier_of(h) == "device"
        assert batches_equal(got, b)
        assert catalog.unspill_count >= 1

    def test_host_overflow_to_disk(self, tmp_path):
        conf = RapidsConf({SPILL_DIR.key: str(tmp_path),
                           HOST_SPILL_STORAGE_SIZE.key: 1})  # 1 byte budget
        cat = BufferCatalog.reset(conf)
        try:
            b = make_batch(500)
            h = cat.add_batch(b)
            cat.synchronous_spill(0)
            assert cat.tier_of(h) == "disk"
            assert cat.disk_bytes > 0
            assert batches_equal(cat.get_batch(h), b)  # disk -> host -> device
            assert cat.tier_of(h) == "device"
        finally:
            cat.close_all()
            BufferCatalog.reset()

    def test_spill_priority_order(self, catalog):
        from spark_rapids_tpu.memory import (ACTIVE_ON_DECK_PRIORITY,
                                             OUTPUT_FOR_SHUFFLE_PRIORITY)
        hi = catalog.add_batch(make_batch(50, 1), ACTIVE_ON_DECK_PRIORITY)
        lo = catalog.add_batch(make_batch(50, 2), OUTPUT_FOR_SHUFFLE_PRIORITY)
        # spill just enough for one buffer: the low-priority one must go
        one = batch_device_bytes(make_batch(50, 2))
        catalog.synchronous_spill(catalog.device_bytes - one)
        assert catalog.tier_of(lo) == "host"
        assert catalog.tier_of(hi) == "device"

    def test_ensure_headroom_spills(self, tmp_path):
        conf = RapidsConf({SPILL_DIR.key: str(tmp_path)})
        cat = BufferCatalog.reset(conf)
        b = make_batch(100)
        size = batch_device_bytes(b)
        DeviceManager.initialize(pool_limit_override=int(size * 1.5))
        try:
            h1 = cat.add_batch(make_batch(100, 1))
            assert cat.ensure_headroom(size)      # must evict h1
            assert cat.tier_of(h1) == "host"
        finally:
            DeviceManager.shutdown()
            cat.close_all()
            BufferCatalog.reset()

    def test_spillable_batch_wrapper(self, catalog):
        b = make_batch(77)
        sb = SpillableColumnarBatch.create(b, catalog=catalog)
        assert sb.num_rows == 77
        catalog.synchronous_spill(0)
        assert batches_equal(sb.get(), b)
        sb.close()
        with pytest.raises(ValueError):
            sb.get()


class TestRetryFramework:
    def test_retry_oom_recovers(self, catalog):
        b = make_batch(64)
        sb = SpillableColumnarBatch.create(b, catalog=catalog)
        calls = {"n": 0}

        def fn(s):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RetryOOM("synthetic")
            return s.get().num_rows_int

        assert with_retry_no_split(sb, fn, catalog=catalog) == 64
        assert calls["n"] == 3

    def test_split_and_retry(self, catalog):
        b = make_batch(64)
        sb = SpillableColumnarBatch.create(b, catalog=catalog)
        failed = {"first": True}

        def fn(s):
            if failed["first"]:
                failed["first"] = False
                raise SplitAndRetryOOM("synthetic")
            return s.get().num_rows_int

        out = list(with_retry([sb], fn, split=split_spillable_in_half,
                              catalog=catalog))
        assert out == [32, 32]

    def test_split_below_one_row_raises(self, catalog):
        sb = SpillableColumnarBatch.create(make_batch(1), catalog=catalog)
        with pytest.raises(SplitAndRetryOOM):
            split_spillable_in_half(sb)

    def test_injection_armed(self, catalog):
        arm_oom_injection(retry=1)
        sb = SpillableColumnarBatch.create(make_batch(10), catalog=catalog)
        calls = {"n": 0}

        def fn(s):
            calls["n"] += 1
            return s.num_rows

        assert with_retry_no_split(sb, fn, catalog=catalog) == 10
        assert calls["n"] == 1  # injection throws before fn on attempt 1

    def test_query_correct_under_oom_injection(self):
        """End-to-end: inject RetryOOM + SplitAndRetryOOM into an aggregate
        query and require identical results (integration-test inject_oom
        marker behavior)."""
        data = {"k": np.arange(1000) % 7, "v": np.arange(1000, dtype=np.float64)}
        from spark_rapids_tpu.sql import functions as F
        s = srt.session()
        df = s.create_dataframe(data)
        expected = df.groupBy("k").agg(F.sum("v").alias("s")) \
                     .orderBy("k").collect()
        conf = RapidsConf({TEST_INJECT_RETRY_OOM.key: 1,
                           TEST_INJECT_SPLIT_OOM.key: 1})
        s2 = srt.session(conf=conf)
        df2 = s2.create_dataframe(data)
        got = df2.groupBy("k").agg(F.sum("v").alias("s")) \
                 .orderBy("k").collect()
        assert got.equals(expected)


class TestSemaphore:
    def test_limits_concurrency(self):
        sem = TpuSemaphore(2)
        active, peak = [0], [0]
        lock = threading.Lock()

        def task(tid):
            sem.acquire_if_necessary(tid)
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.02)
            with lock:
                active[0] -= 1
            sem.release_if_necessary(tid)

        threads = [threading.Thread(target=task, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert peak[0] <= 2
        assert sem.active_tasks() == 0

    def test_reentrant_per_task(self):
        sem = TpuSemaphore(1)
        sem.acquire_if_necessary(7)
        sem.acquire_if_necessary(7)   # no deadlock: deduped
        assert sem.holds(7)
        sem.release_if_necessary(7)
        assert sem.holds(7)           # still held (depth 2)
        sem.release_if_necessary(7)
        assert not sem.holds(7)


class TestTaskCompletion:
    def test_dedup_and_fire(self):
        stc = ScalableTaskCompletion()
        fired = []
        owner = object()
        assert stc.on_task_completion(1, owner, lambda: fired.append("a"))
        assert not stc.on_task_completion(1, owner, lambda: fired.append("b"))
        assert stc.on_task_completion(1, object(), lambda: fired.append("c"))
        stc.task_completed(1)
        assert fired == ["a", "c"]
        assert stc.pending(1) == 0


class TestRealAllocatorHookup:
    """DeviceMemoryEventHandler analog: real allocation failure -> spill ->
    retry -> split (VERDICT r1 #10)."""

    def test_add_batch_raises_split_when_batch_exceeds_pool(self, tmp_path):
        from spark_rapids_tpu.config import SPILL_DIR, RapidsConf
        from spark_rapids_tpu.memory.device import DeviceManager
        from spark_rapids_tpu.memory.retry import SplitAndRetryOOM
        conf = RapidsConf({SPILL_DIR.key: str(tmp_path)})
        cat = BufferCatalog.reset(conf)
        b = make_batch(100)
        size = batch_device_bytes(b)
        DeviceManager.initialize(pool_limit_override=size // 2)
        try:
            with pytest.raises(SplitAndRetryOOM):
                cat.add_batch(b)
        finally:
            DeviceManager.shutdown()
            cat.close_all()
            BufferCatalog.reset()

    def test_oversized_input_survives_via_retry_split(self, tmp_path):
        """with_retry + split halves a batch that cannot fit the pool."""
        from spark_rapids_tpu.config import SPILL_DIR, RapidsConf
        from spark_rapids_tpu.memory.device import DeviceManager
        from spark_rapids_tpu.memory.retry import (split_spillable_in_half,
                                                   with_retry)
        conf = RapidsConf({SPILL_DIR.key: str(tmp_path)})
        cat = BufferCatalog.reset(conf)
        big = make_batch(400)
        DeviceManager.initialize(
            pool_limit_override=batch_device_bytes(big) * 4)
        try:
            sb = SpillableColumnarBatch.create(big, catalog=cat)
            seen_rows = []

            def consume(s):
                got = s.get()
                # registering a copy simulates an op output that must fit
                h = cat.add_batch(got)
                cat.remove(h)
                seen_rows.append(got.num_rows_int)
                return got.num_rows_int

            # shrink the pool below ONE whole batch so the copy can only
            # ever fit after the input is split in half
            DeviceManager.initialize(
                pool_limit_override=int(batch_device_bytes(big) * 0.9))
            total = sum(with_retry([sb], consume, split_spillable_in_half))
            assert total == 400
            assert len(seen_rows) >= 2  # was split at least once
        finally:
            DeviceManager.shutdown()
            cat.close_all()
            BufferCatalog.reset()

    def test_device_oom_guard_spills_and_retries(self):
        from spark_rapids_tpu.memory import oom_guard as G

        class XlaRuntimeError(Exception):
            pass

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                                      "allocating 1048576 bytes")
            return 42

        before = G.STATS["oom_retry_ok"]
        assert G.guard_device_oom(flaky)() == 42
        assert calls["n"] == 2
        assert G.STATS["oom_retry_ok"] == before + 1

    def test_device_oom_guard_escalates_to_split(self):
        from spark_rapids_tpu.memory import oom_guard as G
        from spark_rapids_tpu.memory.retry import SplitAndRetryOOM

        class XlaRuntimeError(Exception):
            pass

        def always_oom():
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory")

        with pytest.raises(SplitAndRetryOOM):
            G.guard_device_oom(always_oom)()

    def test_guard_passes_through_other_errors(self):
        from spark_rapids_tpu.memory import oom_guard as G

        def boom():
            raise ValueError("not an oom")

        with pytest.raises(ValueError):
            G.guard_device_oom(boom)()


class TestFatalDeviceErrors:
    """GpuCoreDumpHandler analog: fatal XlaRuntimeErrors capture a
    diagnostics bundle and surface as FatalDeviceError (never entering
    the OOM spill/retry protocol)."""

    def _fake_xla_error(self, msg):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        return XlaRuntimeError(msg)

    def test_fatal_classification(self):
        from spark_rapids_tpu.memory.fatal import is_fatal_device_error
        assert is_fatal_device_error(self._fake_xla_error("INTERNAL: boom"))
        assert not is_fatal_device_error(
            self._fake_xla_error("RESOURCE_EXHAUSTED: out of memory"))
        assert not is_fatal_device_error(ValueError("x"))

    def test_guard_raises_fatal_with_dump(self, tmp_path):
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.memory.fatal import FatalDeviceError
        from spark_rapids_tpu.memory.oom_guard import guard_device_oom
        s = srt.session(**{"spark.rapids.tpu.fatalDump.path": str(tmp_path)})
        try:
            err = self._fake_xla_error("INTERNAL: compilation blew up")

            def kernel():
                raise err
            from spark_rapids_tpu.sql.physical.base import TaskContext
            with pytest.raises(FatalDeviceError) as ei, \
                    TaskContext(0, s._conf).as_current():
                guard_device_oom(kernel)()
            assert ei.value.dump_path and os.path.exists(ei.value.dump_path)
            body = open(ei.value.dump_path).read()
            assert "compilation blew up" in body
            assert "spill catalog" in body
        finally:
            srt.session(**{"spark.rapids.sql.enabled": True})

    def test_oom_still_routes_to_retry_protocol(self):
        from spark_rapids_tpu.memory import fatal as FT
        from spark_rapids_tpu.memory.oom_guard import guard_device_oom
        from spark_rapids_tpu.memory.retry import SplitAndRetryOOM
        before = FT.STATS["fatal_errors"]
        err = self._fake_xla_error("RESOURCE_EXHAUSTED: out of memory")

        def kernel():
            raise err
        with pytest.raises(SplitAndRetryOOM):
            guard_device_oom(kernel)()
        assert FT.STATS["fatal_errors"] == before  # not classified fatal


class TestLeakDetection:
    """Spill-catalog leak tracking (MemoryCleaner analog): queries must
    leave no registered buffers behind, and debug mode names the site."""

    def test_queries_leak_no_buffers(self):
        import pyarrow as pa
        from spark_rapids_tpu.memory.spill import BufferCatalog
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.sql import functions as F
        BufferCatalog.reset()
        s = srt.session()
        df = s.create_dataframe(pa.table({
            "k": list(range(100)), "v": [float(i) for i in range(100)]}),
            num_partitions=4)
        (df.filter(df.v > 10).groupBy("k")
         .agg(F.sum(F.col("v")).alias("s")).orderBy("k").collect())
        leaks = BufferCatalog.get().leak_report()
        assert leaks == [], leaks

    def test_debug_mode_records_origin(self):
        import numpy as np
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.column import make_fixed_column
        from spark_rapids_tpu.memory.spill import (BufferCatalog,
                                                   SpillableColumnarBatch)
        import spark_rapids_tpu as srt
        try:
            s = srt.session(**{"spark.rapids.memory.gpu.debug": True})
            cat = BufferCatalog.reset(s._conf)
            col = make_fixed_column(T.LONG, np.arange(8))
            b = ColumnarBatch.make(("x",), (col,), 8)
            sb = SpillableColumnarBatch.create(b, catalog=cat)
            rep = cat.leak_report()
            assert len(rep) == 1
            assert "test_memory" in rep[0]["origin"]
            sb.close()
            assert cat.leak_report() == []
        finally:
            srt.session(**{"spark.rapids.sql.enabled": True})
            BufferCatalog.reset()
