"""Planned queries over the 8-device virtual mesh: ShuffleExchangeExec
routes through the compiled all_to_all data plane (parallel/mesh.py), the
engine-level analog of the reference's UCX device-direct shuffle
(RapidsShuffleClient.scala / GpuShuffleExchangeExecBase.scala:266-277).

Oracle: the same query on the default (local) shuffle plane + pandas."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.parallel import mesh as M
from spark_rapids_tpu.sql import functions as F

ICI_CONF = {"spark.rapids.shuffle.mode": "ICI",
            "spark.sql.shuffle.partitions": 8,
            # small test shapes must still exercise the mesh data plane
            # (AQE would rightly coalesce them to one partition)
            "spark.sql.adaptive.coalescePartitions.minRows": 0}


@pytest.fixture()
def ici_sess():
    return srt.session(**ICI_CONF)


def make_tables(rng, n=4000):
    left = pa.table({
        "k": rng.integers(0, 200, n),
        "v": rng.random(n),
        "s": [f"name{i % 101}" for i in range(n)],
    })
    right = pa.table({
        "k": pa.array(np.arange(150), type=pa.int64()),
        "w": pa.array(np.arange(150) * 10.0),
    })
    return left, right


def test_mesh_groupby_agg_matches_local(ici_sess, rng):
    left, _ = make_tables(rng)
    before = M.STATS["mesh_exchanges"]
    df = ici_sess.create_dataframe(left, num_partitions=8)
    got = (df.groupBy("k")
           .agg(F.sum(df.v).alias("sv"), F.count("*").alias("c"),
                F.max(df.v).alias("mx"))
           .orderBy("k").collect().to_pandas())
    assert M.STATS["mesh_exchanges"] > before, "exchange did not ride mesh"
    exp = (left.to_pandas().groupby("k")
           .agg(sv=("v", "sum"), c=("v", "size"), mx=("v", "max"))
           .reset_index())
    assert np.array_equal(got["k"], exp["k"])
    assert np.array_equal(got["c"], exp["c"])
    assert np.allclose(got["sv"], exp["sv"])
    assert np.allclose(got["mx"], exp["mx"])


def test_mesh_shuffled_join_matches_pandas(ici_sess, rng):
    left, right = make_tables(rng)
    before = M.STATS["mesh_exchanges"]
    # force a shuffled hash join (defeat broadcast with a tiny threshold)
    sess = srt.session(**ICI_CONF,
                       **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    l = sess.create_dataframe(left, num_partitions=8)
    r = sess.create_dataframe(right, num_partitions=4)
    got = (l.join(r, on="k", how="inner")
           .select(l.k, l.v, r.w)
           .orderBy("k", "v").collect().to_pandas())
    assert M.STATS["mesh_exchanges"] > before
    exp = (left.to_pandas().merge(right.to_pandas(), on="k", how="inner")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["k"], exp["k"])
    assert np.allclose(got["v"], exp["v"])
    assert np.allclose(got["w"], exp["w"])


def test_mesh_sort_range_partitioned(ici_sess, rng):
    """orderBy over the mesh: RangePartitioning pids + all_to_all."""
    left, _ = make_tables(rng)
    before = M.STATS["mesh_exchanges"]
    df = ici_sess.create_dataframe(left, num_partitions=8)
    got = df.orderBy("k", "v").select(df.k, df.v).collect().to_pandas()
    exp = (left.to_pandas()[["k", "v"]]
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert np.array_equal(got["k"], exp["k"])
    assert np.allclose(got["v"], exp["v"])
    # global sort may use range exchange or a single-partition merge —
    # only assert mesh usage when a multi-partition exchange happened
    assert M.STATS["mesh_exchanges"] >= before


def test_mesh_string_and_null_columns_roundtrip(ici_sess, rng):
    n = 1000
    ks = rng.integers(0, 40, n)
    vs = rng.random(n)
    vs_null = [None if i % 7 == 0 else float(v) for i, v in enumerate(vs)]
    t = pa.table({"k": ks, "v": pa.array(vs_null, type=pa.float64()),
                  "s": [f"x{'y' * (i % 13)}{i % 5}" for i in range(n)]})
    before = M.STATS["mesh_exchanges"]
    df = ici_sess.create_dataframe(t, num_partitions=8)
    got = (df.groupBy("s").agg(F.count(df.v).alias("c"),
                               F.sum(df.v).alias("sv"))
           .orderBy("s").collect().to_pandas())
    assert M.STATS["mesh_exchanges"] > before
    exp = (t.to_pandas().groupby("s")
           .agg(c=("v", "count"), sv=("v", "sum")).reset_index())
    assert list(got["s"]) == list(exp["s"])
    assert np.array_equal(got["c"], exp["c"])
    assert np.allclose(got["sv"], exp["sv"])


def test_mesh_repartition_preserves_rows(ici_sess, rng):
    n = 3000
    t = pa.table({"k": rng.integers(0, 1000, n), "v": rng.random(n)})
    before = M.STATS["mesh_exchanges"]
    df = ici_sess.create_dataframe(t, num_partitions=8)
    got = df.repartition(8, "k").collect()
    assert M.STATS["mesh_exchanges"] > before
    assert got.num_rows == n
    a = sorted(zip(got["k"].to_pylist(), got["v"].to_pylist()))
    b = sorted(zip(t["k"].to_pylist(), t["v"].to_pylist()))
    assert a == b


@pytest.fixture(scope="module")
def tpcds_rig():
    """TPC-DS tables + ICI session amortized across the star-join cases
    (same pattern as scaletest.run_suite's table cache)."""
    from spark_rapids_tpu.testing import scaletest as ST
    t = ST.build_tpcds_tables(6000)
    sess = srt.session(**ICI_CONF,
                       **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    return ST, t, sess


@pytest.mark.parametrize("qname", ["tpcds_q3_star_join",
                                   "tpcds_q19_brand_rev",
                                   "tpcds_q42_cat_rev"])
def test_mesh_tpcds_star_joins(qname, tpcds_rig):
    """BASELINE milestone-3 analog: TPC-DS star-join query shapes executed
    over the 8-device mesh — every shuffle exchange rides the compiled
    all_to_all ICI plane, results checked against the rig's pandas oracle
    (reference target: TPC-DS join subset on 8 chips, BASELINE.md)."""
    ST, t, sess = tpcds_rig
    fn = dict(ST.QUERIES)[qname]
    before = M.STATS["mesh_exchanges"]
    fn(sess, t, F)  # oracle asserts inside
    assert M.STATS["mesh_exchanges"] > before, \
        "star join did not ride the mesh data plane"


def test_mesh_rollup(ici_sess, rng):
    """Grouping sets over the mesh: Expand feeds a mesh-exchanged
    aggregate; every level must match pandas."""
    left, _ = make_tables(rng)
    before = M.STATS["mesh_exchanges"]
    df = ici_sess.create_dataframe(left, num_partitions=8)
    got = (df.rollup("k")
           .agg(F.sum(df.v).alias("sv"), F.grouping_id().alias("gid"))
           .collect().to_pandas())
    assert M.STATS["mesh_exchanges"] > before
    pdf = left.to_pandas()
    l1 = pdf.groupby("k").agg(sv=("v", "sum")).reset_index()
    assert len(got) == len(l1) + 1
    g0 = got[got.gid == 0].sort_values("k").reset_index(drop=True)
    assert np.array_equal(g0["k"], l1["k"])
    assert np.allclose(g0["sv"], l1["sv"])
    assert np.isclose(float(got[got.gid == 1]["sv"].iloc[0]), pdf.v.sum())


def test_mesh_subquery_semi_join(ici_sess, rng):
    """EXISTS/IN rewrites produce semi/anti joins that ride the mesh."""
    left, right = make_tables(rng)
    sess = srt.session(**ICI_CONF,
                       **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    sess.create_dataframe(left, num_partitions=8) \
        .createOrReplaceTempView("mesh_l")
    sess.create_dataframe(right, num_partitions=4) \
        .createOrReplaceTempView("mesh_r")
    before = M.STATS["mesh_exchanges"]
    got = sess.sql(
        "SELECT k, count(*) AS c FROM mesh_l WHERE k IN "
        "(SELECT k FROM mesh_r WHERE w > 500) GROUP BY k ORDER BY k"
    ).collect().to_pandas()
    assert M.STATS["mesh_exchanges"] > before
    lp, rp = left.to_pandas(), right.to_pandas()
    keys = set(rp.k[rp.w > 500])
    exp = (lp[lp.k.isin(keys)].groupby("k").size()
           .sort_index().reset_index(name="c"))
    assert np.array_equal(got["k"], exp["k"])
    assert np.array_equal(got["c"], exp["c"])


def test_mesh_rides_when_partitions_exceed_devices(session):
    """nt=16 partitions on an 8-device mesh: rows route to their owner
    device over ICI, then split locally — the exchange must still ride
    the mesh plane (VERDICT r2 weak #8) with exact results."""
    from spark_rapids_tpu.parallel import mesh as MESH
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    sess = srt.session(**{
        "spark.rapids.shuffle.mode": "ICI",
        "spark.sql.shuffle.partitions": 16,
        "spark.sql.adaptive.enabled": False})
    try:
        rng = np.random.default_rng(0)
        n, G = 120_000, 3_000
        t = pa.table({"k": rng.integers(0, G, n), "v": rng.random(n)})
        df = sess.create_dataframe(t, num_partitions=8)
        before = MESH.STATS["mesh_exchanges"]
        got = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"))
               .collect().to_pandas().sort_values("k").reset_index(drop=True))
        assert MESH.STATS["mesh_exchanges"] > before, \
            "exchange did not ride the mesh plane at nt=16 on 8 devices"
        m = sess.last_query_metrics
        assert m.get("meshExchanges", 0) >= 1
        exp = (t.to_pandas().groupby("k").agg(s=("v", "sum"))
               .reset_index().sort_values("k").reset_index(drop=True))
        assert np.array_equal(got["k"].values, exp["k"].values)
        assert np.allclose(got["s"].values, exp["s"].values)
    finally:
        srt.session(**{"spark.rapids.shuffle.mode": "MULTITHREADED",
                       "spark.sql.shuffle.partitions": 8,
                       "spark.sql.adaptive.enabled": True})
