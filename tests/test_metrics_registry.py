"""Metrics registry (observability/metrics.py) + query flight recorder
(observability/history.py): histogram bucketing and quantiles, thread
safety under the PR 5 parallel-scheduler shape, ~0 off-overhead,
Prometheus/JSON export schema, cardinality bound, session wiring
(query/session labels, query_history) — ISSUE 8 tier-1 coverage."""

import json
import math
import os
import threading

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.observability import history as OH
from spark_rapids_tpu.observability import metrics as OM
from spark_rapids_tpu.sql import functions as F


@pytest.fixture
def registry():
    """Fresh process registry with the flag ON, restored afterwards."""
    prev = OM.METRICS["on"]
    reg = OM.get_registry()
    reg.reset(max_series=4096)
    reg.set_default_labels()
    OM.METRICS["on"] = True
    yield reg
    OM.METRICS["on"] = prev
    reg.reset()
    reg.set_default_labels()


# --------------------------------------------------------------------------
# histogram bucketing + quantiles
# --------------------------------------------------------------------------

def test_bucket_index_bounds_cover_values():
    """Every value lands in a bucket whose upper bound is >= value and
    (for in-range values) whose lower neighbour is < value."""
    for v in (1e-9, 0.001, 0.06104, 0.5, 1.0, 1.5, 2.0, 3.7, 1000.0,
              1048576.0, 1e12):
        i = OM._bucket_index(v)
        assert v <= OM.BUCKET_BOUNDS[i] or i == len(OM.BUCKET_BOUNDS) - 1
        if 0 < i < len(OM.BUCKET_BOUNDS) - 1 \
                and v <= OM.BUCKET_BOUNDS[-2]:
            assert v > OM.BUCKET_BOUNDS[i - 1]
    # exact powers of two sit at their own bound (le semantics)
    assert OM.BUCKET_BOUNDS[OM._bucket_index(1.0)] == 1.0
    assert OM.BUCKET_BOUNDS[OM._bucket_index(256.0)] == 256.0
    # non-positive and NaN land in bucket 0 instead of raising
    assert OM._bucket_index(0.0) == 0
    assert OM._bucket_index(-5.0) == 0
    assert OM._bucket_index(float("nan")) == 0


def test_histogram_count_sum_min_max_and_quantiles(registry):
    values = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0]
    for v in values:
        registry.observe("lat_ms", v)
    snap = registry.json_snapshot()["histograms"]
    assert len(snap) == 1
    h = snap[0]
    assert h["name"] == "lat_ms"
    assert h["count"] == 10
    assert h["sum"] == pytest.approx(sum(values))
    assert h["min"] == 1.0 and h["max"] == 512.0
    # log-bucketed quantiles: p50 in the middle decades, p99 near max
    assert 4.0 <= h["p50"] <= 64.0
    assert h["p95"] >= 128.0
    assert h["p99"] >= h["p95"]
    assert h["p99"] <= 512.0  # never outside the observed range


def test_histogram_quantile_single_value(registry):
    registry.observe("one", 42.0)
    h = registry.json_snapshot()["histograms"][0]
    assert h["p50"] == 42.0 and h["p99"] == 42.0


# --------------------------------------------------------------------------
# thread safety (the PR 5 parallel-scheduler shape: pool workers feeding
# one registry concurrently)
# --------------------------------------------------------------------------

def test_thread_safety_exact_accounting(registry):
    n_threads, per_thread = 8, 400
    barrier = threading.Barrier(n_threads)

    def work(t):
        barrier.wait()
        for i in range(per_thread):
            registry.inc("ops_total")
            registry.observe("op_ms", float(i % 37) + 0.5, worker=str(t))

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = registry.json_snapshot()
    counter = [c for c in snap["counters"] if c["name"] == "ops_total"]
    assert counter[0]["value"] == n_threads * per_thread
    hists = [h for h in snap["histograms"] if h["name"] == "op_ms"]
    assert len(hists) == n_threads  # one series per worker label
    assert sum(h["count"] for h in hists) == n_threads * per_thread


# --------------------------------------------------------------------------
# off-overhead ~ 0: the disabled path records nothing and does no work
# beyond one flag lookup
# --------------------------------------------------------------------------

def test_disabled_feeds_are_noops():
    prev = OM.METRICS["on"]
    OM.METRICS["on"] = False
    reg = OM.get_registry()
    reg.reset()
    try:
        OM.inc("should_not_exist", 5)
        OM.observe("nor_this", 1.0)
        OM.set_gauge("nor_that", 2.0)
        snap = reg.json_snapshot()
        assert snap["counters"] == [] and snap["histograms"] == [] \
            and snap["gauges"] == []
    finally:
        OM.METRICS["on"] = prev


def test_metrics_off_by_default_query_records_nothing():
    reg = OM.get_registry()
    reg.reset()
    sess = srt.session(**{"spark.rapids.tpu.metrics.enabled": False})
    df = sess.create_dataframe(pa.table({"k": [1, 2, 1, 3]}))
    df.groupBy("k").count().collect()
    assert OM.METRICS["on"] is False
    snap = reg.json_snapshot()
    assert snap["counters"] == [] and snap["histograms"] == []


# --------------------------------------------------------------------------
# export schema
# --------------------------------------------------------------------------

def _parse_prom(text):
    """{series_name: [(labels_str, value)]} + type lines."""
    series, types = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split()
            types[name] = typ
            continue
        assert not line.startswith("#")
        metric, val = line.rsplit(" ", 1)
        series.setdefault(metric, []).append(val)
    return series, types


def test_prometheus_export_schema(registry):
    registry.inc("frames_total", 3, plane="local")
    registry.set_gauge("ring_fill", 0.5)
    for v in (1.0, 10.0, 100.0):
        registry.observe("wait_ms", v, exec="TpuSort")
    text = registry.prometheus_text()
    series, types = _parse_prom(text)
    assert types["srt_frames_total"] == "counter"
    assert types["srt_ring_fill"] == "gauge"
    assert types["srt_wait_ms"] == "histogram"
    assert 'srt_frames_total{plane="local"}' in series
    # histogram contract: cumulative non-decreasing buckets, +Inf bucket,
    # _sum and _count present and consistent
    buckets = [(k, int(v[0])) for k, v in series.items()
               if k.startswith("srt_wait_ms_bucket")]
    assert any('le="+Inf"' in k for k, _ in buckets)
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    assert int(series['srt_wait_ms_count{exec="TpuSort"}'][0]) == 3
    assert float(series['srt_wait_ms_sum{exec="TpuSort"}'][0]) == 111.0
    inf_count = [v for k, v in buckets if 'le="+Inf"' in k][0]
    assert inf_count == 3


def test_prometheus_counter_total_suffix_not_doubled(registry):
    registry.inc("a_total")
    registry.inc("b")
    text = registry.prometheus_text()
    assert "srt_a_total " in text and "srt_a_total_total" not in text
    assert "srt_b_total " in text


def test_json_snapshot_schema(registry):
    registry.inc("c", 2, k="v")
    registry.observe("h", 1.5)
    snap = registry.json_snapshot()
    assert json.loads(json.dumps(snap)) == snap  # JSON-clean
    assert snap["counters"][0] == {"name": "c", "labels": {"k": "v"},
                                   "value": 2}
    h = snap["histograms"][0]
    for field in ("name", "labels", "count", "sum", "p50", "p95", "p99"):
        assert field in h
    assert snap["dropped_series"] == 0


def test_max_series_cardinality_bound(registry):
    registry.reset(max_series=3)
    for i in range(10):
        registry.inc("exploding", 1, label=str(i))
    snap = registry.json_snapshot()
    assert len(snap["counters"]) == 3
    assert snap["dropped_series"] == 7
    # existing series still accumulate past the cap
    registry.inc("exploding", 1, label="0")
    snap = registry.json_snapshot()
    assert [c for c in snap["counters"]
            if c["labels"]["label"] == "0"][0]["value"] == 2


def test_default_labels_merge_and_override(registry):
    registry.set_default_labels(session="s1", query=7)
    registry.inc("x")
    registry.inc("y", 1, session="override")
    snap = registry.json_snapshot()
    by_name = {c["name"]: c["labels"] for c in snap["counters"]}
    assert by_name["x"] == {"session": "s1", "query": "7"}
    assert by_name["y"]["session"] == "override"


# --------------------------------------------------------------------------
# session wiring: per-query labels, parallel scheduler, flight recorder
# --------------------------------------------------------------------------

def _query(sess, parts=2):
    rng = np.random.default_rng(5)
    n = 8000
    fact = pa.table({"fk": rng.integers(0, 200, n), "x": rng.random(n)})
    dim = pa.table({"pk": np.arange(200, dtype=np.int64),
                    "cat": rng.integers(0, 8, 200)})
    f = sess.create_dataframe(fact, num_partitions=parts)
    d = sess.create_dataframe(dim)
    return (f.join(d, f.fk == d.pk, "inner").groupBy("cat")
            .agg(F.count("*").alias("n")).orderBy("cat"))


def test_session_feeds_registry_with_labels():
    OM.get_registry().reset()
    sess = srt.session(**{"spark.rapids.tpu.metrics.enabled": True})
    _query(sess).collect()
    assert OM.METRICS["on"] is False  # restored after the query
    snap = sess.metrics_snapshot()
    counters = {c["name"]: c for c in snap["counters"]}
    assert "device_dispatches_total" in counters
    assert counters["device_dispatches_total"]["value"] >= 1
    labels = counters["device_dispatches_total"]["labels"]
    assert labels["session"] == sess.session_id
    assert labels["query"]
    assert any(c["name"] == "queries_total" for c in snap["counters"])
    assert any(h["name"] == "query_ms" for h in snap["histograms"])
    prom = sess.metrics_prometheus()
    assert "srt_device_dispatches_total{" in prom


def test_metrics_with_tracer_spans_and_parallel_scheduler():
    """metrics + tracing + task.parallelism=4: pool workers feed span
    histograms concurrently without breaking accounting."""
    OM.get_registry().reset()
    sess = srt.session(**{"spark.rapids.tpu.metrics.enabled": True,
                          "spark.rapids.tpu.trace.sink": "memory",
                          "spark.rapids.tpu.task.parallelism": 4})
    got = _query(sess, parts=4).collect()
    assert got.num_rows == 8
    snap = sess.metrics_snapshot()
    spans = [h for h in snap["histograms"] if h["name"] == "trace_span_ms"]
    assert spans, snap["histograms"]
    assert all(h["labels"].get("cat") for h in spans)
    # exec label rides the span series (per-exec distributions)
    assert any(h["labels"].get("exec", "").startswith(("Tpu", "Cpu", "("))
               for h in spans)


def test_metrics_flag_restored_on_failure():
    prev = OM.METRICS["on"]
    sess = srt.session(**{"spark.rapids.tpu.metrics.enabled": True})
    f = F.udf(lambda a: {}[a], returnType=srt.DOUBLE)
    df = sess.create_dataframe(pa.table({"a": [1.0]}))
    with pytest.raises(Exception):
        df.select(f(df.a).alias("b")).collect()
    assert OM.METRICS["on"] == prev


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

def test_query_history_records_and_bounds(tmp_path):
    sess = srt.session(**{"spark.rapids.tpu.history.maxQueries": 3,
                          "spark.rapids.tpu.trace.sink": "memory"})
    q = _query(sess)
    for _ in range(5):
        q.collect()
    hist = sess.query_history()
    assert len(hist) == 3  # ring bound
    rec = hist[-1]
    assert rec["status"] == "ok"
    assert rec["session"] == sess.session_id
    assert rec["duration_ms"] > 0
    assert rec["plan_fingerprint"]
    assert rec["trace_summary"]["sync_count"] >= 0
    assert "kernelCacheHits" in rec["metrics"]
    # same query shape -> same fingerprint across runs
    assert hist[0]["plan_fingerprint"] == rec["plan_fingerprint"]
    assert sess.query_history(1) == [rec]


def test_query_history_disk_ring_compacts(tmp_path):
    path = str(tmp_path / "hist" / "ring.jsonl")
    h = OH.QueryHistory(max_queries=4, path=path)
    for i in range(12):
        h.record({"query": i, "ts": i})
    recs = OH.read_history_file(path)
    assert len(recs) <= 2 * 4
    assert recs[-1]["query"] == 11
    # the newest max_queries are always present
    got = [r["query"] for r in recs]
    assert got == sorted(got)
    assert set(range(8, 12)) <= set(got)


def test_query_history_failed_query_recorded():
    sess = srt.session()
    f = F.udf(lambda a: {}[a], returnType=srt.DOUBLE)
    df = sess.create_dataframe(pa.table({"a": [1.0]}))
    with pytest.raises(Exception):
        df.select(f(df.a).alias("b")).collect()
    hist = sess.query_history()
    assert hist and hist[-1]["status"] == "failed"
    assert "error" in hist[-1]


def test_history_disabled_records_nothing():
    sess = srt.session(**{"spark.rapids.tpu.history.enabled": False})
    sess.create_dataframe(pa.table({"k": [1]})).collect()
    assert sess.query_history() == []


def test_plan_fingerprint_distinguishes_shapes():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({"k": [1, 2], "v": [1.0, 2.0]}))
    p1 = sess.physical_plan(df.groupBy("k").count())
    p2 = sess.physical_plan(df.orderBy("k"))
    assert OH.plan_fingerprint(p1) != OH.plan_fingerprint(p2)
    assert OH.plan_fingerprint(p1) == OH.plan_fingerprint(
        sess.physical_plan(df.groupBy("k").count()))
