"""ML handoff (ColumnarRdd / InternalColumnarRddConverter analog +
BASELINE milestone 5's ml-integration path): a query's device output
flows zero-copy into jax training."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import ml
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def test_columnar_rdd_returns_device_batches(sess):
    import jax
    df = sess.create_dataframe(pa.table({
        "a": [1.0, 2.0, 3.0], "b": [4.0, 5.0, 6.0]}), num_partitions=2)
    batches = ml.columnar_rdd(df.select((df.a * 2).alias("a2"), df.b))
    assert sum(b.num_rows_int for b in batches) == 3
    for b in batches:
        for c in b.columns:
            assert isinstance(c.data, jax.Array)  # device-resident
    vals = sorted(v for b in batches
                  for v in np.asarray(b.columns[0].data[:b.num_rows_int])
                  .tolist())
    assert vals == [2.0, 4.0, 6.0]


def test_columnar_rdd_rejects_host_plans(sess):
    s = srt.session(**{"spark.rapids.sql.enabled": False})
    try:
        df = s.create_dataframe(pa.table({"a": [1.0]}))
        with pytest.raises(ValueError, match="device"):
            ml.columnar_rdd(df.select((df.a + 1).alias("b")))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})


def test_to_features_shapes_and_values(sess):
    df = sess.create_dataframe(pa.table({
        "x1": [1.0, 2.0, 3.0, 4.0], "x2": [0.5, 1.5, 2.5, 3.5],
        "y": [1.0, 0.0, 1.0, 0.0]}), num_partitions=2)
    X, y = ml.to_features(df, ["x1", "x2"], "y")
    assert X.shape == (4, 2) and y.shape == (4,)
    assert sorted(np.asarray(X[:, 0]).tolist()) == [1.0, 2.0, 3.0, 4.0]


def test_end_to_end_training_on_engine_output(sess):
    """Engine query -> zero-copy features -> jax gradient descent learns
    the planted linear relationship."""
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    n = 4000
    x1 = rng.random(n); x2 = rng.random(n)
    noise = rng.normal(0, 0.01, n)
    t = pa.table({"x1": x1, "x2": x2,
                  "target": 3.0 * x1 - 2.0 * x2 + 0.5 + noise,
                  "grp": rng.integers(0, 4, n)})
    df = sess.create_dataframe(t, num_partitions=4)
    # feature engineering THROUGH the engine, then handoff
    feats = df.filter(df.grp >= 0).select(
        df.x1, df.x2, (df.x1 * df.x2).alias("x1x2"), df.target)
    X, y = ml.to_features(feats, ["x1", "x2", "x1x2"], "target")
    Xb = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)

    def loss(w):
        return jnp.mean((Xb @ w - y) ** 2)

    g = jax.jit(jax.grad(loss))
    w = jnp.zeros(4, X.dtype)
    for _ in range(800):
        w = w - 0.5 * g(w)
    w = np.asarray(w)
    assert abs(w[0] - 3.0) < 0.1, w
    assert abs(w[1] + 2.0) < 0.1, w
    assert abs(w[2]) < 0.2, w
    assert abs(w[3] - 0.5) < 0.15, w


def test_to_features_rejects_nulls(sess):
    df = sess.create_dataframe(pa.table({
        "x": pa.array([1.0, None, 3.0], type=pa.float64()),
        "y": [1.0, 2.0, 3.0]}))
    with pytest.raises(ValueError, match="NULL"):
        ml.to_features(df, ["x"], "y")
    # filtering the nulls in the query makes it fine
    X, y = ml.to_features(df.filter(df.x.isNotNull()), ["x"], "y")
    assert X.shape == (2, 1)


def test_to_features_rejects_string_label(sess):
    df = sess.create_dataframe(pa.table({"x": [1.0], "s": ["a"]}))
    with pytest.raises(ValueError, match="not numeric"):
        ml.to_features(df, ["x"], "s")


def test_to_torch_handoff(sess):
    import torch
    rng = np.random.default_rng(4)
    t = pa.table({"a": rng.random(200), "b": rng.random(200),
                  "y": rng.random(200)})
    df = sess.create_dataframe(t)
    X, y = ml.to_torch(df, ["a", "b"], "y")
    assert isinstance(X, torch.Tensor) and X.shape == (200, 2)
    assert isinstance(y, torch.Tensor) and y.shape == (200,)
    assert np.allclose(X[:, 0].numpy(), t["a"].to_numpy().astype(np.float32))


def test_minibatch_iterator_shuffles_per_epoch(sess):
    rng = np.random.default_rng(5)
    t = pa.table({"a": rng.random(64), "y": rng.random(64)})
    df = sess.create_dataframe(t)
    batches = list(ml.minibatches(df, ["a"], "y", batch_size=16, epochs=2))
    assert len(batches) == 8  # 4 per epoch x 2 epochs
    assert all(x.shape == (16, 1) and yy.shape == (16,)
               for x, yy in batches)
    e1 = np.concatenate([np.asarray(yy) for _, yy in batches[:4]])
    e2 = np.concatenate([np.asarray(yy) for _, yy in batches[4:]])
    assert sorted(e1.tolist()) == sorted(e2.tolist())  # same data...
    assert not np.array_equal(e1, e2)  # ...different order per epoch


def test_fit_linear_regression_recovers_weights(sess):
    rng = np.random.default_rng(6)
    n = 2000
    a = rng.random(n).astype(np.float32)
    b = rng.random(n).astype(np.float32)
    y = 3.0 * a - 2.0 * b + 0.5
    t = pa.table({"a": a, "b": b, "y": y})
    # ETL in the engine (filter keeps it a real query), training on device
    df = sess.create_dataframe(t).filter(F.col("a") >= 0.0)
    w, bias, mse = ml.fit_linear_regression(df, ["a", "b"], "y",
                                            steps=400, lr=0.3)
    assert mse < 1e-3
    assert abs(float(w[0]) - 3.0) < 0.05
    assert abs(float(w[1]) + 2.0) < 0.05
    assert abs(float(bias) - 0.5) < 0.05


def test_gradient_boosting_multi_batch_device_resident(sess):
    """BASELINE config 5 depth (VERDICT r3 #10): a GBT-shaped model
    trains on MULTI-BATCH engine output with the training data resident
    on device throughout, and actually fits a nonlinear target a linear
    model cannot."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu import ml
    rng = np.random.default_rng(5)
    n = 6000
    x1, x2 = rng.random(n) * 4 - 2, rng.random(n) * 4 - 2
    # nonlinear, axis-aligned target: ideal for trees, hopeless for OLS
    y = np.where((x1 > 0) ^ (x2 > 0.5), 3.0, -1.0) + rng.normal(0, .05, n)
    t = pa.table({"x1": x1, "x2": x2, "y": y})
    df = sess.create_dataframe(t, num_partitions=4)  # multi-batch input
    q = df.filter(df.x1 > -10)  # through the engine, stays on device
    from spark_rapids_tpu.ml import columnar_rdd
    assert len(columnar_rdd(q.select("x1", "x2", "y"))) > 1, \
        "input must arrive as multiple device batches"
    X, yv = ml.to_features(q, ["x1", "x2"], "y")
    assert isinstance(X, jax.Array)  # device residency of training data
    predict, model, mse = ml.fit_gradient_boosting(
        q, ["x1", "x2"], "y", n_trees=25, max_depth=3)
    var = float(jnp.var(yv))
    assert mse < 0.15 * var, (mse, var)   # fits the XOR-ish structure
    _w, _b, lin_mse = ml.fit_linear_regression(q, ["x1", "x2"], "y")
    assert mse < 0.25 * lin_mse, (mse, lin_mse)  # beats linear soundly
    # jitted inference on fresh device data
    Xq = jnp.stack([jnp.asarray([1.0, -1.0]),
                    jnp.asarray([-1.5, 1.0])], axis=1).T
    preds = np.asarray(predict(jnp.asarray(Xq)))
    assert preds.shape == (2,)


def test_to_features_sharded_multichip(sess):
    """Partitioned handoff: (X, y) come back row-sharded over the
    virtual 8-device mesh, ready for pjit training with no resharding."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu import ml
    from spark_rapids_tpu.parallel.mesh import device_mesh
    if len(jax.devices()) < 2:
        import pytest as _p
        _p.skip("needs the multi-device CPU mesh")
    rng = np.random.default_rng(6)
    n = 1001  # deliberately NOT divisible by the device count
    t = pa.table({"a": rng.random(n), "b": rng.random(n),
                  "y": rng.random(n)})
    df = sess.create_dataframe(t, num_partitions=3)
    X, y, live = ml.to_features_sharded(df, ["a", "b"], "y")
    mesh = device_mesh()
    n_dev = mesh.devices.size
    assert live == n and X.shape[0] % n_dev == 0
    assert len(X.sharding.device_set) == n_dev  # genuinely row-sharded
    assert len(y.sharding.device_set) == n_dev
    # a sharded reduction consumes it without host gather
    mask = jnp.arange(X.shape[0]) < live
    tot = float(jnp.sum(jnp.where(mask, y, 0.0)))
    exp = float(np.sum(t["y"].to_numpy()))
    # float32 feature dtype: tolerance scales with the magnitude
    assert abs(tot - exp) < 1e-4 * max(abs(exp), 1.0)
