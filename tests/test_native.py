"""Native C++ library (string packing, Spark-exact hash oracle, xxhash64
frame checksum) + the Pallas murmur3 kernel in interpret mode.  The C++
hashes serve as an INDEPENDENT oracle for the device kernels — three
implementations (C++, jnp, Pallas) must agree bit-for-bit."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import native as N
from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.pallas_kernels import murmur3_long_pallas


def test_native_library_builds():
    assert N.available(), "g++ toolchain present but native build failed"


def test_pack_unpack_strings_roundtrip(rng):
    strs = ["", "a", "hello world", "x" * 63, "é中ñ", "tab\there"] * 50
    flat = b"".join(s.encode() for s in strs)
    lens = [len(s.encode()) for s in strs]
    offsets = np.zeros(len(strs) + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    width = 64
    cap = 512
    packed = N.pack_strings(np.frombuffer(flat, np.uint8), offsets, width,
                            cap)
    assert packed is not None
    matrix, lens_out = packed
    assert matrix.shape == (cap, width)
    assert list(lens_out[:len(strs)]) == lens
    flat2, offs2 = N.unpack_strings(matrix, lens_out, len(strs))
    assert bytes(flat2) == flat
    assert list(offs2) == list(offsets)


def test_native_pack_matches_python_path(rng):
    """arrow_to_device must produce identical matrices with and without
    the native fast path."""
    from spark_rapids_tpu.columnar import convert as C
    strs = [None, "", "abc", "x" * 30, "é中", "end"] * 20
    arr = pa.array(strs, type=pa.string())
    native = C._strings_to_matrix(arr, 256)
    lib = N._lib
    try:
        N._lib = None  # force the numpy fallback
        fallback = C._strings_to_matrix(arr, 256)
    finally:
        N._lib = lib
    assert np.array_equal(native[0], fallback[0])
    assert np.array_equal(native[1], fallback[1])


def test_cpp_murmur3_matches_device_kernel(rng):
    vals = np.concatenate([
        rng.integers(-(1 << 62), 1 << 62, 1000),
        np.array([0, 1, -1, (1 << 63) - 1, -(1 << 63), 42])]).astype(np.int64)
    cpp = N.murmur3_i64(vals, 42)
    assert cpp is not None
    dev = np.asarray(H.murmur3_long(np, vals, np.uint32(42)))
    assert np.array_equal(cpp, dev), "C++ oracle disagrees with jnp kernel"


def test_cpp_murmur3_i32_matches(rng):
    vals = rng.integers(-(1 << 31), 1 << 31, 500).astype(np.int32)
    cpp = N.murmur3_i32(vals, 42)
    dev = np.asarray(H.murmur3_int(np, vals, np.uint32(42)))
    assert np.array_equal(cpp, dev)


def test_pallas_murmur3_interpret_matches(rng):
    import jax.numpy as jnp
    vals = rng.integers(-(1 << 62), 1 << 62, 3000).astype(np.int64)
    pal = np.asarray(murmur3_long_pallas(jnp.asarray(vals), 42,
                                         interpret=True))
    ref = np.asarray(H.murmur3_long(jnp, jnp.asarray(vals), jnp.uint32(42)))
    cpp = N.murmur3_i64(vals, 42)
    assert np.array_equal(pal, ref)
    assert np.array_equal(pal, cpp)


def test_xxhash64_native_matches_python():
    for data in (b"", b"a", b"hello", b"x" * 31, b"y" * 32, b"z" * 100,
                 bytes(range(256)) * 5):
        lib = N._lib if N.available() else None
        native = N.xxhash64_bytes(data, seed=7)
        py = N._xxhash64_py(data, 7)
        assert native == py, data[:10]


def test_serializer_checksum_detects_corruption():
    from spark_rapids_tpu.columnar.convert import arrow_to_device
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    t = pa.table({"x": list(range(100)), "s": [f"v{i}" for i in range(100)]})
    frame = serialize_batch(arrow_to_device(t))
    # round-trip intact
    out = deserialize_batch(frame)
    assert out.num_rows_int == 100
    # flip a payload byte -> loud failure
    bad = bytearray(frame)
    bad[len(bad) // 2] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        deserialize_batch(bytes(bad))


def test_pallas_seg_sum_interpret_matches(rng):
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.pallas_kernels import seg_sum_f32_pallas
    n, s, out = 10_000, 4, 37
    vals = rng.random((s, n)).astype(np.float32)
    rank = rng.integers(0, out + 5, n).astype(np.int32)  # incl. dead ranks
    got = np.asarray(seg_sum_f32_pallas(jnp.asarray(vals),
                                        jnp.asarray(rank), out,
                                        interpret=True))
    exp = np.zeros((s, out), np.float64)
    live = rank < out
    for i in range(s):
        np.add.at(exp[i], rank[live], vals[i][live].astype(np.float64))
    assert got.shape == (s, out)
    assert np.allclose(got, exp, rtol=1e-5)


def test_pallas_seg_sum_single_slot_and_tiny(rng):
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.pallas_kernels import seg_sum_f32_pallas
    vals = np.asarray([[1.0, 2.0, 4.0]], np.float32)
    rank = np.asarray([0, 1, 0], np.int32)
    got = np.asarray(seg_sum_f32_pallas(jnp.asarray(vals),
                                        jnp.asarray(rank), 2,
                                        interpret=True))
    assert np.allclose(got, [[5.0, 2.0]])
