"""Tracing, metric levels, failure dumps, docgen, and the version-shim
provider system (reference §5 aux subsystems + §2.11)."""

import os

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


def _q(sess, n=5000):
    rng = np.random.default_rng(2)
    t = pa.table({"k": rng.integers(0, 20, n), "v": rng.random(n)})
    df = sess.create_dataframe(t, num_partitions=2)
    return df.groupBy("k").agg(F.sum(df.v).alias("s")).orderBy("k")


def test_query_metrics_collected():
    sess = srt.session()
    _q(sess).collect()
    m = sess.last_query_metrics
    assert m, "no metrics collected"
    assert any(k.startswith("d2h") or k.startswith("h2d")
               or "Batches" in k for k in m), m


def test_metrics_level_essential_drops_moderate():
    sess = srt.session(**{"spark.rapids.sql.metrics.level": "ESSENTIAL"})
    _q(sess).collect()
    moderate = sess.last_query_metrics
    # the default metrics are tagged MODERATE; ESSENTIAL drops them
    assert all(not k.startswith(("h2d", "d2h")) for k in moderate), moderate


def test_trace_annotation_smoke():
    """trace.enabled must execute the TraceAnnotation path end-to-end
    (the flag was dead in round 1 — VERDICT §weak 9)."""
    sess = srt.session(**{"spark.rapids.tpu.trace.enabled": True})
    out = _q(sess).collect()
    assert out.num_rows == 20


def test_dump_on_error(tmp_path):
    sess = srt.session(**{"spark.rapids.sql.debug.dumpPath": str(tmp_path)})
    t = pa.table({"a": [1.0, 2.0]})
    df = sess.create_dataframe(t)
    f = F.udf(lambda a: {}[a], returnType=srt.DOUBLE)  # raises KeyError
    with pytest.raises(KeyError):
        df.select(f(df.a).alias("r")).collect()
    dumps = list(tmp_path.iterdir())
    assert dumps, "no failure dump written"
    assert any((d / "error.txt").exists() for d in dumps)


def test_docgen_writes_files(tmp_path):
    from spark_rapids_tpu.docgen import generate
    written = generate(str(tmp_path))
    assert len(written) == 5
    cfg = (tmp_path / "docs" / "configs.md").read_text()
    assert "spark.rapids.sql.batchSizeBytes" in cfg
    ops = (tmp_path / "docs" / "supported_ops.md").read_text()
    assert "ShuffleExchangeExec" in ops and "RegExpReplace" in ops
    csv = (tmp_path / "tools" / "generated_files"
           / "supportedExprs.csv").read_text()
    assert csv.count("\n") > 150  # expression breadth


def test_shim_provider_selection():
    import jax
    from spark_rapids_tpu import shims
    shim = shims.get_shim()
    assert shim.matches(shims._jax_version())
    # the shimmed APIs are callable and functional.  shard_map uses the
    # same availability skip as tests/test_shuffle.py: some environments'
    # jax exposes no shard_map entry point at all, and tier-1 must be
    # green-or-skip there.
    try:
        sm = shim.shard_map()
    except (ImportError, AttributeError):
        pytest.skip("shard_map unavailable in this environment")
    assert callable(sm)
    tm = shim.tree_map()
    assert tm(lambda x: x + 1, {"a": 1}) == {"a": 2}
    leaves, treedef = shim.tree_flatten()({"a": 1, "b": 2})
    assert shim.tree_unflatten()(treedef, leaves) == {"a": 1, "b": 2}


def test_shim_version_ranges():
    from spark_rapids_tpu.shims import JaxLegacyShim, JaxModernShim
    assert JaxLegacyShim.matches((0, 4, 30))
    assert JaxLegacyShim.matches((0, 5, 2))
    assert not JaxLegacyShim.matches((0, 6, 0))
    assert JaxModernShim.matches((0, 6, 0))
    assert JaxModernShim.matches((0, 7, 1))
    assert not JaxModernShim.matches((0, 5, 9))


def test_api_validation_contract_clean():
    """api_validation analog (reference ApiValidation.scala): the current
    build satisfies its recorded exec/expression contract and the running
    jax exposes every entry point the shims lean on."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import api_validation as av
    problems = av.check()
    assert problems == [], problems


def test_per_rule_enable_flags():
    """Per-expression and per-exec enable flags force host placement
    (reference: auto-generated conf per GpuOverrides rule)."""
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    try:
        s = srt.session(**{"spark.rapids.sql.expression.Upper": False})
        df = s.create_dataframe(pa.table({"s": ["ab"]}))
        q = df.select(F.upper(df.s).alias("u"))
        assert "disabled" in s.explain(q)
        assert q.collect()["u"].to_pylist() == ["AB"]  # host still answers
        s2 = srt.session(**{"spark.rapids.sql.exec.ProjectExec": False})
        df2 = s2.create_dataframe(pa.table({"x": [1]}))
        assert "disabled" in s2.explain(df2.select((df2.x + 1).alias("y")))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})


def test_collect_aggs_planned_on_device():
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    s = srt.session()
    df = s.create_dataframe(pa.table({"k": [1], "v": [1.0]}))
    ex = s.explain(df.groupBy("k").agg(F.collect_list(df.v).alias("l")))
    assert "TpuHashAggregate" in ex


def test_query_profile_report(session):
    import numpy as np
    import pyarrow as pa

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    sess = srt.session(**{"spark.rapids.tpu.profile.enabled": True})
    rng = np.random.default_rng(0)
    df = sess.create_dataframe(pa.table({"k": rng.integers(0, 5, 10_000),
                                         "v": rng.random(10_000)}))
    q = df.filter(df.v > 0.5).groupBy("k").agg(F.sum(df.v).alias("s"))
    q.collect()
    report = sess.profile_last_query()
    lines = report.splitlines()
    assert "incl_ms" in lines[0] and "batches" in lines[0]
    assert len(lines) >= 3  # at least a sink + a scan
    assert "Scan" in report
    # profiling off -> no accounting overhead path
    sess2 = srt.session()
    df2 = sess2.create_dataframe(pa.table({"a": [1, 2]}))
    df2.collect()
    assert "exec" in sess2.profile_last_query()


def test_public_assert_framework(session):
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.testing import (
        assert_equal_with_pandas, assert_tpu_and_cpu_are_equal_collect)
    from spark_rapids_tpu.sql import functions as F
    rng = np.random.default_rng(1)
    t = pa.table({"k": rng.integers(0, 4, 500), "v": rng.random(500)})
    df = session.create_dataframe(t)
    q = df.groupBy("k").agg(F.sum(df.v).alias("s"))
    assert_tpu_and_cpu_are_equal_collect(q, sort_by=["k"])
    exp = (t.to_pandas().groupby("k").agg(s=("v", "sum")).reset_index())
    assert_equal_with_pandas(q, exp, sort_by=["k"], rtol=1e-6)


def test_fallback_assert(session):
    import pyarrow as pa

    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.testing import assert_tpu_fallback_collect
    df = session.create_dataframe(pa.table({"a": [2, 3]}))
    # sequence is documented host-only -> its Generate falls back
    q = df.select(F.explode(F.sequence(F.lit(1), df.a)).alias("x"))
    out = assert_tpu_fallback_collect(q, "Generate")
    assert out.num_rows == 5
