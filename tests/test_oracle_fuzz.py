"""Independent-oracle harness: generated data at 100k rows, the ENGINE's
device path vs PANDAS (a genuinely independent engine — the reference's
tier-1 model where CPU Spark is the oracle, asserts.py:560).  The engine's
own numpy backend shares kernels with the device path and cannot catch
shared bugs (VERDICT r1 weak #6); pandas can.

OOM injection is armed for every query so the retry/spill machinery is
exercised at scale (reference conftest inject_oom)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.testing import (ArrayGen, BooleanGen, DateGen,
                                      DoubleGen, IntegerGen, LongGen,
                                      StringGen, StructGen, gen_table)

N = 100_000

OOM_CONF = {
    "spark.rapids.sql.test.injectRetryOOM": 3,
    "spark.rapids.sql.test.injectSplitAndRetryOOM": 5,
}


@pytest.fixture(scope="module")
def data():
    return gen_table({
        "i": IntegerGen(min_val=-10_000, max_val=10_000),
        "l": LongGen(min_val=-(1 << 40), max_val=1 << 40),
        "d": DoubleGen(no_nans=True, no_extremes=True),
        "g": IntegerGen(min_val=0, max_val=500, nullable=False),
        "s": StringGen(max_len=16),
        "b": BooleanGen(),
        "dt": DateGen(),
    }, N, seed=42)


@pytest.fixture(scope="module")
def sess():
    yield srt.session(**OOM_CONF)
    # drop the injection-armed session so later modules' srt.session()
    # doesn't inherit synthetic OOMs (they can land on unsplittable
    # 1-row batches and fail unrelated tests)
    srt.session(**{k: 0 for k in OOM_CONF})


def _df(sess, data):
    return sess.create_dataframe(data, num_partitions=4)


def test_arithmetic_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.select(df.i, (df.i + df.l).alias("add"),
                     (df.d * 2.0 - 1.0).alias("mul"),
                     (-df.i).alias("neg"))
           .collect().to_pandas())
    pdf = data.to_pandas()
    exp_add = pdf["i"] + pdf["l"]
    assert np.allclose(got["add"].to_numpy(np.float64),
                       exp_add.to_numpy(np.float64), equal_nan=True)
    exp_mul = pdf["d"] * 2.0 - 1.0
    assert np.allclose(got["mul"].to_numpy(np.float64),
                       exp_mul.to_numpy(np.float64), equal_nan=True)


def test_filter_and_predicates_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.filter((df.i > 0) & df.b & df.d.isNotNull())
           .select(df.i, df.d).collect().to_pandas())
    pdf = data.to_pandas()
    exp = pdf[(pdf.i > 0) & (pdf.b == True) & pdf.d.notna()  # noqa: E712
              & pdf.i.notna() & pdf.b.notna()]
    assert len(got) == len(exp)
    assert sorted(got["i"].tolist()) == sorted(exp["i"].tolist())


def test_groupby_agg_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.groupBy("g")
           .agg(F.count("*").alias("c"), F.sum(df.d).alias("sd"),
                F.min(df.i).alias("mn"), F.max(df.i).alias("mx"),
                F.avg(df.d).alias("av"))
           .orderBy("g").collect().to_pandas())
    pdf = data.to_pandas()
    exp = (pdf.groupby("g")
           .agg(c=("g", "size"), sd=("d", "sum"), mn=("i", "min"),
                mx=("i", "max"), av=("d", "mean")).reset_index())
    assert np.array_equal(got["g"], exp["g"])
    assert np.array_equal(got["c"], exp["c"])
    assert np.allclose(got["sd"], exp["sd"], rtol=1e-9)
    # pandas min/max skip nulls like Spark
    assert np.array_equal(got["mn"].to_numpy(np.float64),
                          exp["mn"].to_numpy(np.float64), equal_nan=True)
    assert np.allclose(got["av"].to_numpy(np.float64),
                       exp["av"].to_numpy(np.float64), equal_nan=True)


def test_strings_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.select(df.s, F.upper(df.s).alias("up"),
                     F.length(df.s).alias("ln"),
                     F.substring(df.s, 2, 3).alias("sub"))
           .collect().to_pandas())
    pdf = data.to_pandas()
    s = pdf["s"]
    exp_up = s.str.upper()
    exp_ln = s.str.len()
    exp_sub = s.str.slice(1, 4)
    for i in range(0, N, 997):  # sampled row-wise compare
        if pd.isna(s.iloc[i]):
            assert pd.isna(got["up"].iloc[i])
            continue
        assert got["up"].iloc[i] == exp_up.iloc[i], i
        assert got["ln"].iloc[i] == exp_ln.iloc[i], i
        assert got["sub"].iloc[i] == exp_sub.iloc[i], i


def test_sort_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.orderBy(df.i.asc(), df.l.desc()).select(df.i, df.l)
           .collect().to_pandas())
    pdf = data.to_pandas()
    # Spark: nulls first for asc; pandas can't express per-key null order
    # with mixed directions, so compare the non-null block
    exp = (pdf[["i", "l"]].dropna(subset=["i"])
           .sort_values(["i", "l"], ascending=[True, False],
                        na_position="first"))
    n_null_i = int(pdf["i"].isna().sum())
    gi = got["i"].to_numpy(np.float64)
    assert np.isnan(gi[:n_null_i]).all()
    assert np.array_equal(gi[n_null_i:],
                          exp["i"].to_numpy(np.float64))


def test_join_vs_pandas(sess, data):
    df = _df(sess, data)
    dim = gen_table({"g": IntegerGen(0, 400, nullable=False),
                     "w": DoubleGen(no_nans=True, no_extremes=True,
                                    nullable=False)},
                    300, seed=7)
    # unique join keys on the build side
    dim = dim.group_by("g").aggregate([("w", "max")]).rename_columns(
        ["g", "w"])
    r = sess.create_dataframe(dim)
    got = (df.join(r, on="g", how="inner").select(df.g, df.i, r.w)
           .collect().to_pandas())
    exp = data.to_pandas().merge(dim.to_pandas(), on="g", how="inner")
    assert len(got) == len(exp)
    assert sorted(got["g"].tolist()) == sorted(exp["g"].tolist())
    assert abs(got["w"].sum() - exp["w"].sum()) < 1e-6 * max(
        1.0, abs(exp["w"].sum()))


def test_datetime_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.select(df.dt, F.year(df.dt).alias("y"),
                     F.month(df.dt).alias("m"),
                     F.dayofmonth(df.dt).alias("dom"))
           .collect().to_pandas())
    pdf = data.to_pandas()
    dt = pd.to_datetime(pdf["dt"])
    for i in range(0, N, 991):
        if pdf["dt"].iloc[i] is None:
            continue
        assert got["y"].iloc[i] == dt.dt.year.iloc[i], i
        assert got["m"].iloc[i] == dt.dt.month.iloc[i], i
        assert got["dom"].iloc[i] == dt.dt.day.iloc[i], i


def test_conditional_vs_pandas(sess, data):
    df = _df(sess, data)
    got = (df.select(
        F.when(df.i > 0, F.lit("pos")).when(df.i < 0, F.lit("neg"))
        .otherwise(F.lit("zero")).alias("sign"))
        .collect().to_pandas())
    pdf = data.to_pandas()
    exp = np.where(pdf["i"] > 0, "pos",
                   np.where(pdf["i"] < 0, "neg", "zero"))
    # null i -> no branch matches -> otherwise("zero")? Spark: null > 0 is
    # null (false-y), so nulls fall through to the otherwise value
    assert (got["sign"].to_numpy() == exp).all()


def test_nested_arrays_roundtrip(sess):
    t = gen_table({
        "u": LongGen(0, 1 << 30, nullable=False),
        "a": ArrayGen(IntegerGen(-100, 100), max_len=5),
        "st": StructGen([("x", IntegerGen(-5, 5)),
                         ("y", StringGen(max_len=6))]),
    }, 20_000, seed=3)
    sess2 = srt.session(**OOM_CONF)
    df = sess2.create_dataframe(t, num_partitions=3)
    got = df.select(df.u, df.a, df.st, F.size(df.a).alias("sz")) \
        .collect().to_pylist()
    exp = t.to_pylist()
    for g, e in zip(got, exp):
        assert g["u"] == e["u"]
        assert g["a"] == e["a"]
        assert g["st"] == e["st"]
        assert g["sz"] == (len(e["a"]) if e["a"] is not None else -1)


def test_rollup_vs_pandas(sess, data):
    """Grouping sets at 100k rows with OOM injection armed: every level's
    sums/counts must match pandas exactly."""
    df = _df(sess, data)
    got = (df.rollup("g", "b")
           .agg(F.sum(df.d).alias("sv"), F.count("*").alias("c"),
                F.grouping_id().alias("gid"))
           .collect().to_pandas())
    pdf = data.to_pandas()
    l0 = (pdf.groupby(["g", "b"], dropna=False)
          .agg(sv=("d", "sum"), c=("d", "size")).reset_index())
    l1 = (pdf.groupby(["g"], dropna=False)
          .agg(sv=("d", "sum"), c=("d", "size")).reset_index())
    assert len(got) == len(l0) + len(l1) + 1
    # key-wise comparison at every level (b is nullable: merge on both
    # keys with NaN-safe equality via fillna sentinels)
    g0 = (got[got.gid == 0].assign(bk=lambda x: x.b.fillna(-1))
          .sort_values(["g", "bk"]).reset_index(drop=True))
    e0 = (l0.assign(bk=lambda x: x.b.fillna(-1))
          .sort_values(["g", "bk"]).reset_index(drop=True))
    assert np.array_equal(g0["g"], e0["g"])
    assert np.array_equal(g0["bk"], e0["bk"])
    assert np.array_equal(g0["c"], e0["c"])
    assert np.allclose(np.asarray(g0["sv"].fillna(0.0)),
                       np.asarray(e0["sv"].fillna(0.0)))
    g1 = got[got.gid == 1].sort_values("g").reset_index(drop=True)
    e1 = l1.sort_values("g").reset_index(drop=True)
    assert np.array_equal(g1["g"], e1["g"])
    assert np.array_equal(g1["c"], e1["c"])
    assert np.allclose(np.asarray(g1["sv"].fillna(0.0)),
                       np.asarray(e1["sv"].fillna(0.0)))
    tot = got[got.gid == 3]
    assert int(tot["c"].iloc[0]) == len(pdf)
    assert np.isclose(float(tot["sv"].iloc[0]), pdf.d.sum())


def test_subquery_predicates_vs_pandas(sess, data):
    """IN / NOT EXISTS subqueries at 100k rows against pandas."""
    df = _df(sess, data)
    df.createOrReplaceTempView("fz_t")
    pdf = data.to_pandas()
    got = sess.sql(
        "SELECT g, count(*) AS c FROM fz_t WHERE g IN "
        "(SELECT g FROM fz_t WHERE d > 0.98) GROUP BY g ORDER BY g"
    ).collect().to_pandas()
    keys = set(pdf.g[pdf.d > 0.98])
    exp = (pdf[pdf.g.isin(keys)].groupby("g").size()
           .sort_index().reset_index(name="c"))
    assert np.array_equal(got["g"], exp["g"])
    assert np.array_equal(got["c"], exp["c"])
    got = sess.sql(
        "SELECT count(*) AS c FROM fz_t a WHERE NOT EXISTS "
        "(SELECT 1 FROM fz_t b WHERE b.g = a.g AND b.d > 0.98)"
    ).collect().to_pylist()[0]["c"]
    assert got == int((~pdf.g.isin(keys)).sum())


def test_scalar_subquery_and_interval_vs_pandas(sess, data):
    df = _df(sess, data)
    df.createOrReplaceTempView("fz_t2")
    pdf = data.to_pandas()
    got = sess.sql(
        "SELECT count(*) AS c FROM fz_t2 WHERE d > "
        "(SELECT avg(d) FROM fz_t2)").collect().to_pylist()[0]["c"]
    assert got == int((pdf.d > pdf.d.mean()).sum())
    got = sess.sql(
        "SELECT count(*) AS c FROM fz_t2 WHERE dt + INTERVAL '1' YEAR "
        "<= CAST('2015-06-01' AS date)").collect().to_pylist()[0]["c"]
    import datetime
    shifted = pd.Series(pdf.dt.dropna()).map(
        lambda x: datetime.date(x.year + 1, x.month,
                                28 if (x.month == 2 and x.day == 29)
                                else x.day))
    assert got == int((shifted <= datetime.date(2015, 6, 1)).sum())


def test_bloom_filtered_star_join_vs_pandas(sess, data):
    """Shuffle join with the bloom runtime filter engaged (small dim,
    broadcast disabled) under OOM injection — results must equal pandas
    exactly; the filter may only DROP non-matching probe rows early."""
    from spark_rapids_tpu.ops import bloom as B
    dim = gen_table({
        "g": IntegerGen(min_val=0, max_val=500, nullable=False),
        "name": StringGen(max_len=8),
    }, 60, seed=7)
    # dedupe dim keys (dim tables are unique-keyed; keeps the oracle 1:1)
    dim = dim.group_by("g").aggregate([("name", "max")]).rename_columns(
        ["g", "name"])
    prev_thr = sess.conf.get("spark.rapids.sql.autoBroadcastJoinThreshold",
                             10 * 1024 * 1024)
    sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    try:
        df = _df(sess, data)
        ddf = sess.create_dataframe(dim, num_partitions=2)
        built0 = B.STATS["blooms_built"]
        got = (df.join(ddf, df.g == ddf.g, "inner")
               .select(df.i, df.g, F.col("name"))
               .collect().to_pandas())
        assert B.STATS["blooms_built"] > built0, "bloom did not engage"
        exp = (data.to_pandas().merge(dim.to_pandas(), on="g",
                                      how="inner")[["i", "g", "name"]])
        assert len(got) == len(exp)
        a = got.sort_values(["i", "g", "name"]).reset_index(drop=True)
        b = exp.sort_values(["i", "g", "name"]).reset_index(drop=True)
        assert a.equals(b.astype(a.dtypes.to_dict()))
    finally:
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      prev_thr)


def test_tdigest_percentile_vs_pandas_quantiles(sess, data):
    """Grouped approx_percentile on the t-digest path under OOM
    injection: each estimate must sit within 3.5% rank error of the
    group's true distribution (pandas as the independent oracle; the
    delta-200 sketch merged across OOM-split batches lands ~2.5%
    worst-case on 200-row groups)."""
    sess.conf.set("spark.rapids.sql.approxPercentile.strategy", "tdigest")
    try:
        df = _df(sess, data)
        got = (df.filter(df.d.isNotNull()).groupBy("g")
               .agg(F.percentile_approx(df.d, [0.25, 0.5, 0.75])
                    .alias("pq"))
               .collect().to_pandas())
        pdf = data.to_pandas()
        pdf = pdf[pdf.d.notna()]
        checked = 0
        for gi in got["g"].head(40):
            gv = np.sort(pdf[pdf.g == gi].d.values)
            if len(gv) < 50:
                continue
            row = got[got.g == gi].pq.iloc[0]
            for est, p in zip(row, [0.25, 0.5, 0.75]):
                rank = np.searchsorted(gv, est) / len(gv)
                assert abs(rank - p) < 0.035, (gi, p, rank)
            checked += 1
        assert checked > 10
    finally:
        sess.conf.set("spark.rapids.sql.approxPercentile.strategy", "auto")


def test_window_functions_vs_pandas(sess, data):
    """Window functions over generated data under OOM injection:
    row_number / whole-partition avg / lag, vs pandas oracles."""
    from spark_rapids_tpu.sql.window_api import Window
    df = _df(sess, data)
    w = Window.partitionBy("g").orderBy("i", "l")
    wp = Window.partitionBy("g")
    got = (df.filter(df.i.isNotNull() & df.l.isNotNull())
           .select(df.g, df.i, df.l, df.d,
                   F.row_number().over(w).alias("rn"),
                   F.avg(df.d).over(wp).alias("ga"),
                   F.lag(df.i, 1).over(w).alias("pi"))
           .collect().to_pandas()
           .sort_values(["g", "i", "l"]).reset_index(drop=True))
    pdf = data.to_pandas()
    pdf = pdf[pdf.i.notna() & pdf.l.notna()].copy()
    pdf = pdf.sort_values(["g", "i", "l"], kind="stable")
    pdf["rn"] = pdf.groupby("g").cumcount() + 1
    pdf["ga"] = pdf.groupby("g").d.transform("mean")
    pdf["pi"] = pdf.groupby("g").i.shift(1)
    exp = pdf.reset_index(drop=True)
    assert len(got) == len(exp)
    assert np.array_equal(got["g"].values, exp["g"].values)
    # ties on (i, l) make rn order-dependent; per group the rank SET must
    # still be exactly 1..n
    for gi in got["g"].unique()[:30]:
        rn = np.sort(got[got.g == gi].rn.values)
        assert np.array_equal(rn, np.arange(1, len(rn) + 1)), gi
    assert np.allclose(got["ga"].values, exp["ga"].values)
    # lag: compare the multiset per group (tie order may differ)
    for gi in got["g"].unique()[:25]:
        a = sorted(got[got.g == gi].pi.dropna().values.tolist())
        b = sorted(exp[exp.g == gi].pi.dropna().values.tolist())
        assert a == b, gi


def test_lateral_view_explode_fuzz_vs_pandas(sess):
    """Randomized LATERAL VIEW [OUTER] explode/posexplode over generated
    nested rows vs pandas explode (VERDICT r3 weak #4: round-3 surfaces
    had example-based tests only)."""
    rng = np.random.default_rng(61)
    n = 4000
    lens = rng.integers(0, 5, n)
    arrs = [None if i % 37 == 0 else
            [int(v) for v in rng.integers(-50, 50, lens[i])]
            for i in range(n)]
    t = pa.table({
        "k": pa.array(rng.integers(0, 30, n), pa.int64()),
        "arr": pa.array(arrs, pa.list_(pa.int64())),
    })
    sess.create_dataframe(t, num_partitions=3).createOrReplaceTempView(
        "lvf_t")
    pdf = t.to_pandas()

    for outer in (False, True):
        kw = "LATERAL VIEW OUTER" if outer else "LATERAL VIEW"
        got = sess.sql(
            f"SELECT k, c FROM lvf_t {kw} explode(arr) x AS c"
        ).collect().to_pandas()
        exp = pdf[["k", "arr"]].explode("arr").rename(columns={"arr": "c"})
        if not outer:
            exp = exp.dropna(subset=["c"])
        else:
            # OUTER keeps null/empty rows with c = NULL — pandas explode
            # already yields NaN for both empty lists and None
            pass
        g = sorted(map(tuple, got.fillna(-10**9).values.tolist()))
        e = sorted((int(k), int(c) if c == c and c is not None else -10**9)
                   for k, c in exp.values.tolist())
        assert g == e, (outer, g[:5], e[:5])

    got = sess.sql(
        "SELECT k, p, c FROM lvf_t LATERAL VIEW posexplode(arr) x AS p, c"
    ).collect().to_pandas()
    rows = []
    for k, arr in pdf[["k", "arr"]].values.tolist():
        if arr is None or (hasattr(arr, "__len__") and len(arr) == 0):
            continue
        for p, c in enumerate(arr):
            rows.append((int(k), p, int(c)))
    assert sorted(map(tuple, got.values.tolist())) == sorted(rows)


def test_tablesample_fuzz_properties(sess):
    """TABLESAMPLE (n PERCENT | n ROWS) REPEATABLE: determinism, subset
    property, and row-count bounds over random fractions."""
    rng = np.random.default_rng(62)
    n = 20_000
    t = pa.table({
        "id": pa.array(list(range(n)), pa.int64()),
        "v": pa.array(rng.random(n)),
    })
    sess.create_dataframe(t, num_partitions=4).createOrReplaceTempView(
        "tsf_t")
    all_ids = set(range(n))
    for trial in range(5):
        pct = int(rng.integers(5, 60))
        seed = int(rng.integers(0, 10_000))
        q = (f"SELECT id FROM tsf_t TABLESAMPLE ({pct} PERCENT) "
             f"REPEATABLE ({seed})")
        a = sess.sql(q).collect().column("id").to_pylist()
        b = sess.sql(q).collect().column("id").to_pylist()
        assert a == b, "REPEATABLE sample must be deterministic"
        assert set(a) <= all_ids and len(set(a)) == len(a)
        # Bernoulli sampling: expect pct% +- 5 sigma
        import math
        sigma = math.sqrt(n * (pct / 100) * (1 - pct / 100))
        assert abs(len(a) - n * pct / 100) < 5 * sigma + 10, (pct, len(a))
    for rows in (17, 1003):
        got = sess.sql(
            f"SELECT id FROM tsf_t TABLESAMPLE ({rows} ROWS)"
        ).collect().num_rows
        assert got == rows


def test_interval_arithmetic_fuzz_vs_pandas(sess):
    """Randomized INTERVAL +/- over date/timestamp columns vs pandas
    DateOffset/timedelta semantics (month arithmetic clamps to month end
    the way Spark does)."""
    rng = np.random.default_rng(63)
    n = 3000
    days = rng.integers(0, 20000, n)
    micros = rng.integers(0, 2**44, n)
    t = pa.table({
        "d": pa.array(days.astype("int32"), pa.date32()),
        "ts": pa.array(micros, pa.timestamp("us")),
    })
    sess.create_dataframe(t, num_partitions=2).createOrReplaceTempView(
        "ivf_t")
    pdf = t.to_pandas()
    for trial in range(4):
        nd = int(rng.integers(1, 400))
        nm = int(rng.integers(1, 30))
        nh = int(rng.integers(1, 100))
        got = sess.sql(
            f"SELECT d + INTERVAL '{nd}' DAY AS d1, "
            f"d - INTERVAL '{nm}' MONTH AS d2, "
            f"ts + INTERVAL '{nh}' HOUR AS t1 "
            f"FROM ivf_t").collect().to_pandas()
        exp_d1 = pdf.d + pd.Timedelta(days=nd)
        exp_d2 = (pd.to_datetime(pdf.d) - pd.DateOffset(months=nm)).dt.date
        exp_t1 = pdf.ts + pd.Timedelta(hours=nh)
        assert (pd.to_datetime(got.d1) ==
                pd.to_datetime(exp_d1)).all(), (trial, nd)
        assert (got.d2 == exp_d2).all(), (trial, nm)
        got_t1 = pd.to_datetime(got.t1)
        if got_t1.dt.tz is not None:      # engine returns tz-aware UTC
            got_t1 = got_t1.dt.tz_localize(None)
        assert (got_t1 == exp_t1).all(), (trial, nh)
