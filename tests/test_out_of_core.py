"""Out-of-core sort + chunked join gather (reference
GpuSortExec.scala:242 GpuOutOfCoreSortIterator, JoinGatherer.scala:730),
exercised with tiny chunk budgets and OOM injection."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical import join as J
from spark_rapids_tpu.sql.physical import sortlimit as SL


def _sorted_df(sess, rng, n=6000, parts=6):
    t = pa.table({
        "k": rng.integers(0, 500, n),
        "v": rng.random(n),
        "s": [f"row{i % 97:03d}" for i in range(n)],
    })
    return sess.create_dataframe(t, num_partitions=parts), t


def test_out_of_core_sort_matches_pandas(rng):
    sess = srt.session(
        **{"spark.rapids.sql.sort.outOfCore.targetRows": 512})
    df, t = _sorted_df(sess, rng)
    before = SL.STATS["ooc_sorts"]
    got = df.orderBy("k", "v").collect().to_pandas()
    assert SL.STATS["ooc_sorts"] > before, "out-of-core path not engaged"
    exp = t.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    assert np.array_equal(got["k"], exp["k"])
    assert np.allclose(got["v"], exp["v"])
    assert list(got["s"]) == list(exp["s"])


def test_out_of_core_sort_desc_with_nulls(rng):
    n = 3000
    vals = [None if i % 11 == 0 else float(v)
            for i, v in enumerate(rng.random(n))]
    t = pa.table({"u": list(range(n)),
                  "v": pa.array(vals, type=pa.float64())})
    sess = srt.session(
        **{"spark.rapids.sql.sort.outOfCore.targetRows": 256})
    df = sess.create_dataframe(t, num_partitions=4)
    before = SL.STATS["ooc_sorts"]
    got = df.orderBy(df.v.desc()).collect().to_pandas()
    assert SL.STATS["ooc_sorts"] > before
    exp = (t.to_pandas().sort_values("v", ascending=False,
                                     na_position="last")
           .reset_index(drop=True))
    # nulls-last for desc (Spark default desc_nulls_last)
    gv, ev = got["v"].to_numpy(), exp["v"].to_numpy()
    assert len(gv) == len(ev)
    m = ~np.isnan(ev)
    assert np.allclose(gv[m], ev[m]) and np.isnan(gv[~m]).all()


def test_out_of_core_sort_with_oom_injection(rng):
    sess = srt.session(**{
        "spark.rapids.sql.sort.outOfCore.targetRows": 512,
        "spark.rapids.sql.test.injectRetryOOM": 2,
        "spark.rapids.sql.test.injectSplitAndRetryOOM": 4,
    })
    df, t = _sorted_df(sess, rng, n=4000, parts=4)
    got = df.orderBy("k", "v").collect().to_pandas()
    exp = t.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    assert np.array_equal(got["k"], exp["k"])
    assert np.allclose(got["v"], exp["v"])


def test_chunked_join_output_matches_pandas(rng):
    n = 2000
    left = pa.table({"k": rng.integers(0, 40, n), "v": rng.random(n)})
    right = pa.table({"k": pa.array(np.arange(40), type=pa.int64()),
                      "w": pa.array(np.arange(40) * 1.5)})
    sess = srt.session(
        **{"spark.rapids.sql.join.outputChunkRows": 256})
    l = sess.create_dataframe(left)
    r = sess.create_dataframe(right)
    before = J.STATS["chunked_joins"]
    got = (l.join(r, on="k", how="inner").select(l.k, l.v, r.w)
           .orderBy("k", "v").collect().to_pandas())
    assert J.STATS["chunked_joins"] > before, "chunked gather not engaged"
    exp = (left.to_pandas().merge(right.to_pandas(), on="k")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["k"], exp["k"])
    assert np.allclose(got["v"], exp["v"])
    assert np.allclose(got["w"], exp["w"])


def test_chunked_left_join_with_unmatched(rng):
    n = 1500
    left = pa.table({"k": rng.integers(0, 60, n), "v": rng.random(n)})
    right = pa.table({"k": pa.array(np.arange(30), type=pa.int64()),
                      "w": pa.array(np.arange(30) * 2.0)})
    sess = srt.session(
        **{"spark.rapids.sql.join.outputChunkRows": 128})
    l = sess.create_dataframe(left)
    r = sess.create_dataframe(right)
    before = J.STATS["chunked_joins"]
    got = (l.join(r, on="k", how="left").select(l.k, l.v, r.w)
           .orderBy("k", "v").collect().to_pandas())
    assert J.STATS["chunked_joins"] > before
    exp = (left.to_pandas().merge(right.to_pandas(), on="k", how="left")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["k"], exp["k"])
    gw, ew = got["w"].to_numpy(), exp["w"].to_numpy()
    m = ~np.isnan(ew)
    assert np.allclose(gw[m], ew[m]) and np.isnan(gw[~m]).all()


def test_chunked_cross_join(rng):
    left = pa.table({"a": list(range(70))})
    right = pa.table({"b": list(range(50))})
    sess = srt.session(
        **{"spark.rapids.sql.join.outputChunkRows": 512})
    l = sess.create_dataframe(left)
    r = sess.create_dataframe(right)
    before = J.STATS["chunked_joins"]
    got = l.crossJoin(r).collect()
    assert J.STATS["chunked_joins"] > before
    assert got.num_rows == 70 * 50
    pairs = set(zip(got["a"].to_pylist(), got["b"].to_pylist()))
    assert len(pairs) == 70 * 50


def test_chunked_join_with_oom_injection(rng):
    n = 1200
    left = pa.table({"k": rng.integers(0, 30, n), "v": rng.random(n)})
    right = pa.table({"k": pa.array(np.arange(30), type=pa.int64()),
                      "w": pa.array(np.arange(30) * 3.0)})
    sess = srt.session(**{
        "spark.rapids.sql.join.outputChunkRows": 256,
        "spark.rapids.sql.test.injectRetryOOM": 3,
    })
    l = sess.create_dataframe(left)
    r = sess.create_dataframe(right)
    got = (l.join(r, on="k", how="inner").select(l.k, l.v, r.w)
           .orderBy("k", "v").collect().to_pandas())
    exp = (left.to_pandas().merge(right.to_pandas(), on="k")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.allclose(got["v"], exp["v"])
