"""Out-of-core machinery driven END-TO-END through the planner by real
scale-rig queries (VERDICT r4 #7): the spill catalog, OOM retry/split and
out-of-core sort paths are covered by unit suites at their seams — this
exercises them through planned joins/aggregates/sorts with the pandas
oracle still checking results.  Reference: inject_oom in every
integration run (conftest.py:113-265) + the out-of-core strategy set
(SURVEY §2.7 item 5)."""

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.memory.spill import BufferCatalog
from spark_rapids_tpu.sql.physical import sortlimit as SL
from spark_rapids_tpu.testing import scaletest

ROWS = 120_000

#: every Nth guarded kernel throws a synthetic RetryOOM (spill-all then
#: retry) / SplitAndRetryOOM (halve the input); tight out-of-core targets
#: force the chunked sort/merge paths even between injections
CONF = {
    "spark.rapids.sql.test.injectRetryOOM": 7,
    "spark.rapids.sql.test.injectSplitAndRetryOOM": 11,
    "spark.rapids.sql.sort.outOfCore.targetRows": 4096,
}


@pytest.fixture(scope="module")
def sess():
    yield srt.session(**CONF)
    # later modules must not inherit armed synthetic OOMs
    srt.session()


@pytest.fixture(scope="module")
def rig(sess):
    """Datagen amortized across the module (run_suite's tables/
    extra_tables contract) — the 120k-row sets build once, not per
    query."""
    return {"tables": scaletest.build_tables(ROWS), "extra": {}}


@pytest.mark.parametrize("query", ["tpch_q9_full", "q3_skewed_left_join",
                                   "q5_global_sort"])
def test_scale_query_exercises_out_of_core(sess, rig, query):
    cat = BufferCatalog.get()
    spills_before = cat.spill_count
    ooc_before = SL.STATS["ooc_sorts"]
    # run_suite embeds the pandas oracle: a return IS a verified result
    rep = scaletest.run_suite(ROWS, queries=[query], sess=sess,
                              tables=rig["tables"],
                              extra_tables=rig["extra"])
    assert len(rep) == 1, f"{query} did not run"
    engaged = (cat.spill_count > spills_before
               or SL.STATS["ooc_sorts"] > ooc_before)
    assert engaged, (
        f"{query} exercised neither the spill catalog "
        f"({spills_before} -> {cat.spill_count}) nor the out-of-core "
        f"sort ({ooc_before} -> {SL.STATS['ooc_sorts']})")


def test_spill_catalog_fires(sess, rig):
    """Self-contained spill proof: real bytes move through the catalog's
    DEVICE->HOST demotion path (synchronousSpill analog) during one
    injected-OOM query — independent of which tests ran before."""
    cat = BufferCatalog.get()
    before = cat.spill_count
    scaletest.run_suite(ROWS, queries=["q2_join_agg"], sess=sess,
                        tables=rig["tables"], extra_tables=rig["extra"])
    assert cat.spill_count > before, "injected OOMs caused no spill"
