"""Grammar fuzz for the Spark-ONLY SQL surface (VERDICT r4 #10): the
sqlite-oracle fuzz (test_sql_grammar_fuzz.py) is constrained to the
dialect intersection — no datetime functions, no DECIMAL, no LATERAL
VIEW.  This harness reuses its type-directed-generator idea with DUAL
EMISSION: every random node produces both SQL text and an independent
pandas evaluation lambda, so the oracle needs no SQL engine at all.

Covered grammar: date arithmetic (date_add/date_sub/last_day), date
extraction (year/month/dayofmonth/quarter/dayofweek/datediff), exact
DECIMAL literals/arithmetic/aggregation, LATERAL VIEW explode, CASE with
three-valued predicates, and GROUP BY over extracted date parts.
"""

import datetime
import random
from decimal import Decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt

N = 3000


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(11)
    base = datetime.date(2019, 1, 1)
    def dates(frac_null):
        days = rng.integers(0, 1500, N)
        mask = rng.random(N) < frac_null
        return [None if m else base + datetime.timedelta(days=int(d))
                for m, d in zip(mask, days)]
    def decs(frac_null):
        cents = rng.integers(-10_000_00, 10_000_00, N)
        mask = rng.random(N) < frac_null
        return pa.array(
            [None if m else Decimal(int(c)).scaleb(-2)
             for m, c in zip(mask, cents)], pa.decimal128(12, 2))
    arrs = []
    for k in range(N):
        r = rng.random()
        if r < 0.08:
            arrs.append(None)
        elif r < 0.16:
            arrs.append([])
        else:
            arrs.append([int(x) for x in
                         rng.integers(-50, 50, rng.integers(1, 5))])
    t = pa.table({
        "dt": pa.array(dates(0.1), pa.date32()),
        "dt2": pa.array(dates(0.15), pa.date32()),
        "j": pa.array(rng.integers(0, 20, N), pa.int64()),
        "dec": decs(0.12),
        "dec2": decs(0.2),
        "arr": pa.array(arrs, pa.list_(pa.int64())),
    })
    sess = srt.session()
    sess.create_dataframe(t, num_partitions=3).createOrReplaceTempView(
        "pg")
    pdf = pd.DataFrame({
        "dt": pd.to_datetime(pd.Series(dates_col(t, "dt"))),
        "dt2": pd.to_datetime(pd.Series(dates_col(t, "dt2"))),
        "j": t.column("j").to_pandas(),
        "dec": pd.Series(t.column("dec").to_pylist(), dtype=object),
        "dec2": pd.Series(t.column("dec2").to_pylist(), dtype=object),
        "arr": pd.Series(t.column("arr").to_pylist(), dtype=object),
    })
    return sess, pdf


def dates_col(t, name):
    return t.column(name).to_pylist()


# --------------------------------------------------------------------------
# Dual-emission generator: node = (sql, fn(pdf) -> Series)
# --------------------------------------------------------------------------


class DualGen:
    def __init__(self, rng: random.Random):
        self.rng = rng

    # ---- dates -----------------------------------------------------------
    def date(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.45:
            col = r.choice(["dt", "dt2"])
            return col, lambda df: df[col]
        d = depth - 1
        pick = r.random()
        if pick < 0.35:
            s, f = self.date(d)
            k = r.randint(0, 200)
            return (f"date_add({s}, {k})",
                    lambda df: f(df) + pd.Timedelta(days=k))
        if pick < 0.6:
            s, f = self.date(d)
            k = r.randint(0, 200)
            return (f"date_sub({s}, {k})",
                    lambda df: f(df) - pd.Timedelta(days=k))
        if pick < 0.8:
            s, f = self.date(d)
            return (f"last_day({s})",
                    lambda df: f(df) + pd.offsets.MonthEnd(0))
        ps, pf = self.pred(d)
        asql, af = self.date(d)
        bsql, bf = self.date(d)
        return (f"(CASE WHEN {ps} THEN {asql} ELSE {bsql} END)",
                lambda df: af(df).where(
                    pf(df).fillna(False).astype(bool), bf(df)))

    # ---- ints (incl. date extraction) ------------------------------------
    def intx(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.3:
            if r.random() < 0.5:
                return "j", lambda df: df["j"].astype("Int64")
            k = r.randint(-30, 30)
            return str(k), lambda df: pd.Series([k] * len(df),
                                                dtype="Int64")
        d = depth - 1
        pick = r.random()
        ds, dfn = self.date(d)
        if pick < 0.12:
            return (f"year({ds})",
                    lambda df: dfn(df).dt.year.astype("Int64"))
        if pick < 0.24:
            return (f"month({ds})",
                    lambda df: dfn(df).dt.month.astype("Int64"))
        if pick < 0.36:
            return (f"dayofmonth({ds})",
                    lambda df: dfn(df).dt.day.astype("Int64"))
        if pick < 0.46:
            return (f"quarter({ds})",
                    lambda df: dfn(df).dt.quarter.astype("Int64"))
        if pick < 0.56:
            # Spark dayofweek: 1 = Sunday .. 7 = Saturday;
            # pandas dayofweek: 0 = Monday .. 6 = Sunday
            return (f"dayofweek({ds})",
                    lambda df: ((dfn(df).dt.dayofweek + 1) % 7 + 1)
                    .astype("Int64"))
        if pick < 0.7:
            bs, bfn = self.date(d)
            return (f"datediff({ds}, {bs})",
                    lambda df: (dfn(df) - bfn(df)).dt.days.astype("Int64"))
        asql, af = self.intx(d)
        bsql, bf = self.intx(d)
        op = r.choice(["+", "-"])
        if op == "+":
            return f"({asql} + {bsql})", lambda df: af(df) + bf(df)
        return f"({asql} - {bsql})", lambda df: af(df) - bf(df)

    # ---- decimals --------------------------------------------------------
    def dec(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.4:
            if r.random() < 0.65:
                col = r.choice(["dec", "dec2"])
                return col, lambda df: df[col]
            lit = Decimal(r.randint(-9999, 9999)).scaleb(-2)
            return (f"CAST('{lit}' AS DECIMAL(10,2))",
                    lambda df: pd.Series([lit] * len(df), dtype=object))
        d = depth - 1
        pick = r.random()
        if pick < 0.3:
            asql, af = self.dec(d)
            bsql, bf = self.dec(d)
            return (f"({asql} + {bsql})",
                    lambda df: _dec_binop(af(df), bf(df),
                                          lambda a, b: a + b))
        if pick < 0.55:
            asql, af = self.dec(d)
            bsql, bf = self.dec(d)
            return (f"({asql} - {bsql})",
                    lambda df: _dec_binop(af(df), bf(df),
                                          lambda a, b: a - b))
        if pick < 0.7:
            # one multiply level only: nested products outgrow DECIMAL(38)
            asql, af = self.dec(0)
            lit = Decimal(r.randint(-300, 300)).scaleb(-2)
            return (f"({asql} * CAST('{lit}' AS DECIMAL(5,2)))",
                    lambda df: _dec_binop(
                        af(df), pd.Series([lit] * len(df), dtype=object),
                        lambda a, b: a * b))
        if pick < 0.82:
            asql, af = self.dec(d)
            return (f"(- {asql})",
                    lambda df: af(df).map(
                        lambda v: None if v is None else -v))
        ps, pf = self.pred(d)
        asql, af = self.dec(d)
        bsql, bf = self.dec(d)
        return (f"(CASE WHEN {ps} THEN {asql} ELSE {bsql} END)",
                lambda df: af(df).where(
                    pf(df).fillna(False).astype(bool), bf(df)))

    # ---- predicates ------------------------------------------------------
    def pred(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.45:
            pick = r.random()
            if pick < 0.3:
                asql, af = self.date(max(depth - 1, 0))
                bsql, bf = self.date(max(depth - 1, 0))
                op = r.choice(["<", "<=", ">", ">=", "="])
                return (f"({asql} {op} {bsql})",
                        lambda df: _cmp(af(df), bf(df), op))
            if pick < 0.6:
                asql, af = self.dec(max(depth - 1, 0))
                bsql, bf = self.dec(max(depth - 1, 0))
                op = r.choice(["<", "<=", ">", ">=", "="])
                return (f"({asql} {op} {bsql})",
                        lambda df: _cmp_obj(af(df), bf(df), op))
            if pick < 0.75:
                asql, af = self.date(max(depth - 1, 0))
                neg = r.random() < 0.5
                sql = f"({asql} IS {'NOT ' if neg else ''}NULL)"
                if neg:
                    return sql, lambda df: af(df).notna()
                return sql, lambda df: af(df).isna()
            asql, af = self.intx(max(depth - 1, 0))
            bsql, bf = self.intx(max(depth - 1, 0))
            op = r.choice(["<", "<=", ">", ">=", "="])
            return (f"({asql} {op} {bsql})",
                    lambda df: _cmp(af(df), bf(df), op))
        d = depth - 1
        asql, af = self.pred(d)
        bsql, bf = self.pred(d)
        pick = r.random()
        if pick < 0.45:
            # Kleene AND over nullable booleans
            return (f"({asql} AND {bsql})",
                    lambda df: _and3(af(df), bf(df)))
        if pick < 0.9:
            return (f"({asql} OR {bsql})",
                    lambda df: _or3(af(df), bf(df)))
        return f"(NOT {asql})", lambda df: ~af(df).astype("boolean")


def _dec_binop(a, b, op):
    return pd.Series(
        [None if (x is None or y is None or
                  (isinstance(x, float)) or (isinstance(y, float)))
         else op(x, y)
         for x, y in zip(a.tolist(), b.tolist())], dtype=object)


def _cmp(a, b, op):
    m = {"<": "lt", "<=": "le", ">": "gt", ">=": "ge", "=": "eq"}[op]
    out = getattr(a, m)(b)
    # comparisons with NaT/NA are UNKNOWN (masked), not False
    na = a.isna() | b.isna()
    return out.astype("boolean").mask(na)


def _cmp_obj(a, b, op):
    import operator
    f = {"<": operator.lt, "<=": operator.le, ">": operator.gt,
         ">=": operator.ge, "=": operator.eq}[op]
    vals = [None if (x is None or y is None) else f(x, y)
            for x, y in zip(a.tolist(), b.tolist())]
    return pd.Series(vals, dtype="boolean")


def _and3(a, b):
    a = a.astype("boolean")
    b = b.astype("boolean")
    return a & b


def _or3(a, b):
    return a.astype("boolean") | b.astype("boolean")


# --------------------------------------------------------------------------
# comparison plumbing
# --------------------------------------------------------------------------


def _norm(v):
    if v is None or v is pd.NaT or (isinstance(v, float) and np.isnan(v)):
        return (1, "")
    if isinstance(v, Decimal):
        if v == 0:
            v = abs(v)  # Decimal('-0') normalizes to '-0'; engine says '0'
        return (0, str(v.normalize()))
    if isinstance(v, (pd.Timestamp, np.datetime64)):
        return (0, pd.Timestamp(v).date().isoformat())
    if isinstance(v, datetime.date):
        return (0, v.isoformat())
    if isinstance(v, (np.integer, int)) or v is pd.NA:
        return (1, "") if v is pd.NA else (0, int(v))
    if isinstance(v, np.bool_):
        return (0, bool(v))
    return (0, v)


def _check(sess, pdf, sql, exp_cols):
    got_tbl = sess.sql(sql).collect()
    got = sorted(tuple(_norm(v) for v in row)
                 for row in zip(*[got_tbl.column(i).to_pylist()
                                  for i in range(got_tbl.num_columns)]))
    want = sorted(tuple(_norm(v) for v in row)
                  for row in zip(*[c.tolist() for c in exp_cols]))
    assert len(got) == len(want), f"{len(got)} != {len(want)}\n{sql}"
    for g, w in zip(got, want):
        assert g == w, f"{g} != {w}\n{sql}"


# --------------------------------------------------------------------------
# fuzz tiers
# --------------------------------------------------------------------------


def test_datetime_project_filter_fuzz(env):
    sess, pdf = env
    rng = random.Random(606)
    g = DualGen(rng)
    for q in range(18):
        nodes = [g.date(2) if rng.random() < 0.5 else g.intx(2)
                 for _ in range(rng.randint(1, 3))]
        psql, pfn = g.pred(2)
        sels = ", ".join(f"{s} AS c{k}" for k, (s, _) in enumerate(nodes))
        sql = f"SELECT {sels} FROM pg WHERE {psql}"
        mask = pfn(pdf).fillna(False).astype(bool).to_numpy()
        _check(sess, pdf, sql, [f(pdf)[mask] for _, f in nodes])


def test_decimal_project_filter_fuzz(env):
    sess, pdf = env
    rng = random.Random(707)
    g = DualGen(rng)
    for q in range(15):
        nodes = [g.dec(2) for _ in range(rng.randint(1, 3))]
        psql, pfn = g.pred(2)
        sels = ", ".join(f"{s} AS c{k}" for k, (s, _) in enumerate(nodes))
        sql = f"SELECT {sels} FROM pg WHERE {psql}"
        mask = pfn(pdf).fillna(False).astype(bool).to_numpy()
        _check(sess, pdf, sql, [f(pdf)[mask] for _, f in nodes])


def test_decimal_group_agg_fuzz(env):
    sess, pdf = env
    rng = random.Random(808)
    g = DualGen(rng)
    for q in range(12):
        keysql, keyfn = rng.choice([
            ("year(dt)", lambda df: df["dt"].dt.year.astype("Int64")),
            ("month(dt)", lambda df: df["dt"].dt.month.astype("Int64")),
            ("j", lambda df: df["j"].astype("Int64")),
        ])
        psql, pfn = g.pred(1)
        sql = (f"SELECT {keysql} AS k0, sum(dec) AS a0, "
               f"count(dec) AS a1, min(dt) AS a2, max(dt2) AS a3, "
               f"count(*) AS a4 "
               f"FROM pg WHERE {psql} GROUP BY {keysql}")
        mask = pfn(pdf).fillna(False).astype(bool).to_numpy()
        sub = pdf[mask].copy()
        sub["__k"] = keyfn(pdf)[mask]
        groups = []
        for k, grp in sub.groupby("__k", dropna=False):
            decs = [v for v in grp["dec"].tolist() if v is not None]
            groups.append((
                None if k is pd.NA else k,
                sum(decs) if decs else None,
                len(decs),
                grp["dt"].min(),
                grp["dt2"].max(),
                len(grp)))
        cols = [pd.Series([r[i] for r in groups], dtype=object)
                for i in range(6)]
        _check(sess, pdf, sql, cols)


# --------------------------------------------------------------------------
# encoded columnar execution parity (ISSUE 6): the same grammar idea over
# LOW-CARDINALITY strings and REPETITIVE ints — the columns the scan keeps
# dictionary/RLE-encoded — with every generated query run encoded-ON vs
# encoded-OFF and the two engines compared bit-identically.  The oracle
# here is the RAW engine itself: the kill switch is structural, so any
# divergence is an encoding bug by definition.
# --------------------------------------------------------------------------

ENC_N = 4000
_ENC_CATS = [f"c{i:02d}" for i in range(12)]


class EncodedGen(DualGen):
    """String/repetitive-int extension used only by the encoded-parity
    fuzz (SQL emission only — the raw engine is the oracle)."""

    def strx(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.5:
            return r.choice(["s", "s2"])
        d = depth - 1
        p = self.epred(d)
        a = self.strx(d)
        b = self.strx(d)
        return f"(CASE WHEN {p} THEN {a} ELSE {b} END)"

    def epred(self, depth: int):
        r = self.rng
        if depth <= 0 or r.random() < 0.5:
            pick = r.random()
            if pick < 0.3:
                a = self.strx(0)
                op = r.choice(["<", "<=", ">", ">=", "=", "<>"])
                lit = r.choice(_ENC_CATS)
                return f"({a} {op} '{lit}')"
            if pick < 0.5:
                a = self.strx(0)
                items = ", ".join(
                    f"'{c}'" for c in r.sample(_ENC_CATS, r.randint(1, 4)))
                return f"({a} IN ({items}))"
            if pick < 0.65:
                a = self.strx(0)
                neg = "NOT " if r.random() < 0.5 else ""
                return f"({a} IS {neg}NULL)"
            if pick < 0.85:
                col = r.choice(["r", "j"])
                op = r.choice(["<", "<=", ">", ">=", "="])
                return f"({col} {op} {r.randint(0, 30)})"
            a, b = self.strx(0), self.strx(0)
            op = r.choice(["<", "=", ">="])
            return f"({a} {op} {b})"
        d = depth - 1
        a, b = self.epred(d), self.epred(d)
        pick = r.random()
        if pick < 0.45:
            return f"({a} AND {b})"
        if pick < 0.9:
            return f"({a} OR {b})"
        return f"(NOT {a})"


def _enc_table():
    rng = np.random.default_rng(23)

    def strs(frac_null):
        idx = rng.integers(0, len(_ENC_CATS), ENC_N)
        mask = rng.random(ENC_N) < frac_null
        return [None if m else _ENC_CATS[i] for m, i in zip(mask, idx)]
    return pa.table({
        "s": pa.array(strs(0.08)),
        "s2": pa.array(strs(0.15)),
        "r": pa.array(np.repeat(
            np.arange(ENC_N // 100, dtype=np.int64), 100)),
        "j": pa.array(rng.integers(0, 20, ENC_N), pa.int64()),
        "v": pa.array(rng.random(ENC_N)),
    })


def _enc_run(sess, sql):
    tbl = sess.sql(sql).collect()
    return sorted(tuple(_norm(v) for v in row)
                  for row in zip(*[tbl.column(i).to_pylist()
                                   for i in range(tbl.num_columns)]))


def test_encoded_vs_raw_parity_fuzz():
    rng = random.Random(404)
    g = EncodedGen(rng)
    queries = []
    for _ in range(16):
        p = g.epred(2)
        if rng.random() < 0.5:
            sels = ", ".join(f"{g.strx(2)} AS c{k}"
                             for k in range(rng.randint(1, 2)))
            queries.append(f"SELECT {sels}, r, v FROM eg WHERE {p}")
        else:
            queries.append(
                f"SELECT s, count(*) AS n, sum(v) AS sv, min(s2) AS m, "
                f"max(r) AS mr FROM eg WHERE {p} GROUP BY s")
    t = _enc_table()
    results = {}
    for on in (True, False):
        sess = srt.session(**{
            "spark.rapids.tpu.sql.encoded.enabled": on,
            "spark.rapids.sql.autoBroadcastJoinThreshold": 1})
        sess.create_dataframe(t, num_partitions=3) \
            .createOrReplaceTempView("eg")
        results[on] = [_enc_run(sess, sql) for sql in queries]
    for sql, enc, raw in zip(queries, results[True], results[False]):
        assert enc == raw, sql


def test_lateral_view_fuzz(env):
    sess, pdf = env
    rng = random.Random(909)
    for q in range(10):
        lo = rng.randint(-50, 20)
        with_where = rng.random() < 0.6
        sql = "SELECT j, x, (x + j) AS y FROM pg " \
              "LATERAL VIEW explode(arr) e AS x"
        if with_where:
            sql += f" WHERE x > {lo}"
        rows = []
        for j, arr in zip(pdf["j"], pdf["arr"]):
            if arr is None:
                continue
            for x in arr:
                if with_where and not (x > lo):
                    continue
                rows.append((j, x, x + j))
        cols = [pd.Series([r[i] for r in rows], dtype=object)
                for i in range(3)]
        _check(sess, pdf, sql, cols)
