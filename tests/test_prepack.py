"""Device-side pre-pack (columnar/prepack.py): narrowing fetches must be
bit-identical to plain fetches across dtypes, null patterns and value
ranges — the wire saving is only real if correctness never depends on it.
Reference analog: nvcomp shuffle codecs round-trip exactly
(``NvcompLZ4CompressionCodec.scala``)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu  # noqa: F401  (platform/config setup)
import jax.numpy as jnp

from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.columnar import prepack
from spark_rapids_tpu.config import RapidsConf


@pytest.fixture(autouse=True)
def force_on():
    """CPU backend defaults prepack to off (auto) — force it on and drop
    the size gate so unit shapes exercise the narrow path."""
    g = RapidsConf.get_global()
    old = (g.get("spark.rapids.tpu.d2h.prepack"),
           g.get("spark.rapids.tpu.d2h.prepack.minBytes"))
    g.set("spark.rapids.tpu.d2h.prepack", "true")
    g.set("spark.rapids.tpu.d2h.prepack.minBytes", 0)
    yield
    g.set("spark.rapids.tpu.d2h.prepack", old[0])
    g.set("spark.rapids.tpu.d2h.prepack.minBytes", old[1])


def _roundtrip(arrs):
    devs = [jnp.asarray(a) for a in arrs]
    out = prepack.prepacked_device_get(devs)
    for a, b in zip(arrs, out):
        assert b.dtype == a.dtype, (b.dtype, a.dtype)
        np.testing.assert_array_equal(np.asarray(b), a)


def test_int_narrowing_ranges():
    rng = np.random.default_rng(0)
    _roundtrip([
        rng.integers(0, 100, 1000),                      # i64 -> i1
        rng.integers(-120, 120, 1000),                   # i64 -> i1 signed
        rng.integers(-30000, 30000, 1000),               # i64 -> i2
        rng.integers(-2**30, 2**30, 1000),               # i64 -> i4
        rng.integers(-2**62, 2**62, 1000),               # i64 keep
        np.array([np.iinfo(np.int64).min, np.iinfo(np.int64).max]),
        np.array([-128, 127], dtype=np.int64),           # exact i8 bounds
        np.array([-129, 127], dtype=np.int64),           # just outside i8
        rng.integers(0, 2**16, 1000).astype(np.uint64),  # u64 -> u2
        np.array([2**63 + 5, 2**64 - 1], dtype=np.uint64),  # u64 keep (big)
        rng.integers(0, 200, 1000).astype(np.int32),     # i4 -> i1
        rng.integers(0, 3, 1000).astype(np.int16),       # i2 -> i1
    ])


def test_bool_bitpack_shapes():
    rng = np.random.default_rng(1)
    _roundtrip([
        rng.random(1000) < 0.5,
        rng.random(7) < 0.5,          # non-multiple-of-8 tail
        np.zeros(0, dtype=bool),      # empty
        (rng.random((64, 3)) < 0.5),  # 2-D
    ])


def test_f64_lossless_and_not():
    rng = np.random.default_rng(2)
    f32_vals = rng.random(1000).astype(np.float32).astype(np.float64)
    full = rng.random(1000)  # generic doubles: NOT f32-representable
    out = prepack.prepacked_device_get(
        [jnp.asarray(f32_vals), jnp.asarray(full)])
    np.testing.assert_array_equal(np.asarray(out[0]), f32_vals)
    # the non-lossless column must ride the keep path bit-exactly
    np.testing.assert_array_equal(np.asarray(out[1]), full)


def test_special_floats_keep_path():
    vals = np.array([np.nan, np.inf, -np.inf, 0.0, -0.0, 1e-300, 1.5])
    out = prepack.prepacked_device_get([jnp.asarray(vals),
                                        jnp.asarray(np.arange(4096))])
    got = np.asarray(out[0])
    np.testing.assert_array_equal(np.isnan(got), np.isnan(vals))
    m = ~np.isnan(vals)
    np.testing.assert_array_equal(got[m], vals[m])


def test_strings_and_f32_pass_through():
    rng = np.random.default_rng(3)
    mat = rng.integers(0, 256, (128, 16)).astype(np.uint8)  # string matrix
    f32 = rng.random(512).astype(np.float32)
    _roundtrip([mat, f32, rng.integers(0, 50, 512)])


def test_batch_roundtrip_through_device_to_arrow():
    """Full batch path: nulls, strings, decimals, dates — table-equal."""
    rng = np.random.default_rng(4)
    n = 2000
    t = pa.table({
        "i": pa.array(rng.integers(0, 100, n),
                      mask=rng.random(n) < 0.1),
        "big": pa.array(rng.integers(-2**60, 2**60, n)),
        "f": pa.array(rng.random(n)),
        "s": pa.array([f"row-{i % 37}" for i in range(n)]),
        "d": pa.array(rng.integers(0, 20000, n).astype("int32"),
                      type=pa.int32()),
    })
    before = dict(prepack.STATS)
    back = device_to_arrow(arrow_to_device(t))
    assert back.equals(t) or all(
        back.column(c).combine_chunks() == t.column(c).combine_chunks()
        for c in t.column_names)
    assert prepack.STATS["prepacked_fetches"] > before["prepacked_fetches"]
    assert prepack.STATS["bytes_on_wire"] > before["bytes_on_wire"]


def test_wire_savings_on_narrow_data():
    """The whole point: low-range int64 + bools must shrink >=3x."""
    rng = np.random.default_rng(5)
    n = 100_000
    devs = [jnp.asarray(rng.integers(0, 50, n)),       # 8 -> 1 byte
            jnp.asarray(rng.integers(0, 1000, n)),     # 8 -> 2
            jnp.asarray(rng.random(n) < 0.5)]          # 1 -> 1/8
    before_wire = prepack.STATS["bytes_on_wire"]
    before_naive = prepack.STATS["bytes_naive"]
    prepack.prepacked_device_get(devs)
    wire = prepack.STATS["bytes_on_wire"] - before_wire
    naive = prepack.STATS["bytes_naive"] - before_naive
    assert naive == n * 17
    assert wire * 3 < naive, (wire, naive)


def test_disabled_falls_back():
    RapidsConf.get_global().set("spark.rapids.tpu.d2h.prepack", "false")
    before = dict(prepack.STATS)
    out = prepack.prepacked_device_get([jnp.asarray(np.arange(100))])
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(100))
    assert prepack.STATS["prepacked_fetches"] == before["prepacked_fetches"]


def test_min_bytes_gate():
    RapidsConf.get_global().set(
        "spark.rapids.tpu.d2h.prepack.minBytes", 10**9)
    before = dict(prepack.STATS)
    out = prepack.prepacked_device_get([jnp.asarray(np.arange(100))])
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(100))
    assert prepack.STATS["prepacked_fetches"] == before["prepacked_fetches"]


def test_shuffle_frame_narrowed(tmp_path):
    """Serializer rides the prepacked fetch; frames stay wire-compatible
    (deserialize restores the original widths)."""
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    rng = np.random.default_rng(6)
    n = 4096
    t = pa.table({"k": rng.integers(0, 9, n),
                  "v": rng.random(n),
                  "flag": rng.random(n) < 0.5})
    b = arrow_to_device(t)
    before = prepack.STATS["prepacked_fetches"]
    frame = serialize_batch(b)
    assert prepack.STATS["prepacked_fetches"] > before
    back = deserialize_batch(frame)
    assert back.num_rows_int == n
    got = device_to_arrow(back)
    for c in t.column_names:
        assert got.column(c).combine_chunks().equals(
            t.column(c).combine_chunks()), c
