"""Radix argsort (ops/radix_sort.py): stable-equality with np.lexsort,
the lex_sort integration under forced modes, and bake-off behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.ops import radix_sort
from spark_rapids_tpu.ops.radix_sort import radix_argsort, supported_keys
from spark_rapids_tpu.ops.ranks import lex_sort


@pytest.mark.parametrize("case", [
    "i64", "i32_small", "two_keys", "bools", "dupes", "empty_range"])
def test_radix_matches_lexsort(case):
    rng = np.random.default_rng(11)
    n = 50_000
    cases = {
        "i64": [rng.integers(-2**62, 2**62, n)],
        "i32_small": [rng.integers(-5, 5, n).astype(np.int32)],
        "two_keys": [rng.integers(0, 40, n),
                     rng.integers(-2**40, 2**40, n)],
        "bools": [rng.integers(0, 2, n).astype(bool)],
        "dupes": [np.repeat(rng.integers(-3, 3, n // 100), 100)],
        "empty_range": [np.zeros(256, np.int64)],
    }
    keys_np = cases[case]
    keys = [jnp.asarray(k) for k in keys_np]
    perm = np.asarray(jax.jit(
        lambda *ks: radix_argsort(jnp, list(ks)))(*keys))
    want = np.lexsort(tuple(reversed(keys_np)))
    assert np.array_equal(perm, want), case   # np.lexsort is stable too


def test_supported_keys_envelope():
    a = jnp.zeros(8, jnp.int64)
    b = jnp.zeros(8, jnp.bool_)
    i8 = jnp.zeros(8, jnp.int8)
    f = jnp.zeros(8, jnp.float64)
    assert supported_keys(jnp, [a])
    assert supported_keys(jnp, [a, a])
    assert not supported_keys(jnp, [a, a, a])     # 192 passes > budget
    # sort_permutation's (dead bool, null int8, value i64) shape fits
    assert supported_keys(jnp, [b, i8, a])
    assert radix_sort.total_passes([b, i8, a]) == 73
    assert not supported_keys(jnp, [f])           # floats go via lax.sort


def test_lex_sort_forced_radix_end_to_end():
    """Force the radix path through lex_sort and a real window query."""
    conf = RapidsConf.get_global()
    old = conf.get("spark.rapids.sql.sort.radix", "auto")
    radix_sort._BAKEOFF.clear()
    conf.set("spark.rapids.sql.sort.radix", "on")
    try:
        rng = np.random.default_rng(5)
        a = jnp.asarray(rng.integers(-1000, 1000, 20_000))
        b = jnp.asarray(rng.integers(0, 7, 20_000))
        perm, skeys = lex_sort(jnp, [b, a])
        want = np.lexsort((np.asarray(a), np.asarray(b)))
        assert np.array_equal(np.asarray(perm), want)

        import pyarrow as pa

        from spark_rapids_tpu.sql import functions as F
        from spark_rapids_tpu.sql.window_api import Window
        sess = srt.session()
        n = 30_000
        t = pa.table({"g": rng.integers(0, 50, n), "v": rng.random(n),
                      "o": rng.integers(0, 10**9, n)})
        df = sess.create_dataframe(t, num_partitions=3)
        w = Window.partitionBy("g").orderBy("o")
        got = (df.select(df.g, F.row_number().over(w).alias("rn"))
               .filter(F.col("rn") <= 2).collect().to_pandas())
        pdf = t.to_pandas().sort_values(["g", "o"]).groupby("g").head(2)
        assert len(got) == len(pdf)
        assert sorted(got.g.tolist()) == sorted(pdf.g.tolist())
    finally:
        conf.set("spark.rapids.sql.sort.radix", str(old))
        radix_sort._BAKEOFF.clear()


def test_bakeoff_picks_a_winner_and_caches():
    radix_sort._BAKEOFF.clear()
    v1 = radix_sort.radix_wins(jnp, 64)
    assert isinstance(v1, (bool, np.bool_))
    assert jax.default_backend() in radix_sort._BAKEOFF
    assert radix_sort.radix_wins(jnp, 64) == v1   # derived from frozen base
    # verdicts scale with pass count off ONE base measurement
    assert isinstance(radix_sort.radix_wins(jnp, 160), (bool, np.bool_))
