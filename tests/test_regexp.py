"""Regex engine + expression tests — reference coverage model:
RegularExpressionTranspilerSuite + integration_tests regexp_test.py.
Oracle: Python re (for supported common patterns, Java and Python agree)."""

import re as pyre

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.ops.regex_engine import (RegexUnsupported,
                                               compile_regex)
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


STRS = ["hello world", "abc123def", "", "aaa", "2021-03-04",
        "foo@bar.com", "x,y,,z", "   spaces   ", "MixedCASE99",
        "tab\there", "dot.dot.dot", "a1b2c3d4"]


def str_df(sess):
    t = pa.table({"u": list(range(len(STRS))), "s": STRS})
    return sess.create_dataframe(t), t


def run_both(df, sort_col="u"):
    sess = df._session
    a = df.collect()
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        b = df.collect()
    finally:
        sess.conf.set("spark.rapids.sql.enabled", True)
    assert a.to_pylist() == b.to_pylist(), "device/host mismatch"
    return a


@pytest.mark.parametrize("pat", [
    r"\d+", r"[a-c]+", r"^a", r"o$", r"world", r"(foo|dot)", r"a{2,3}",
    r"\w+@\w+\.\w+", r"\d{4}-\d{2}-\d{2}", r"\s+", r"[^,]+", r".",
    r"(?:ab)+c?", r"[A-Z][a-z]+",
])
def test_rlike_matches_python_re(sess, pat):
    df, t = str_df(sess)
    out = run_both(df.select(df.u, F.rlike(df.s, pat).alias("m"))).to_pylist()
    exp = [bool(pyre.search(pat, s)) for s in STRS]
    assert [r["m"] for r in out] == exp, pat


def test_rlike_runs_on_device(sess):
    df, t = str_df(sess)
    report = sess.explain(df.select(df.u, F.rlike(df.s, r"\d+").alias("m")))
    assert "cannot run" not in report


def test_unsupported_patterns_fall_back(sess):
    df, t = str_df(sess)
    for pat, frag in [(r"(a)\1", "backreference"),
                      (r"a(?=b)", "group construct"),
                      (r"a*+b", "possessive"),
                      # Java's \Z matches before a final line terminator;
                      # the device $ is strict end-of-input (advisor r3)
                      (r"ab\Z", "anchor"),
                      (r"\bword", "anchor")]:
        q = df.select(df.u, F.rlike(df.s, pat).alias("m"))
        report = sess.explain(q)
        assert "cannot run on TPU" in report, pat
        assert frag in report, (pat, report)
    # lazy quantifiers stay host-side for SPAN-consuming expressions
    # (they change the match extent) ...
    rep = sess.explain(df.select(
        F.regexp_replace(df.s, r"a*?b", "X").alias("r")))
    assert "cannot run on TPU" in rep and "lazy" in rep


def test_rlike_lazy_and_input_anchors_on_device(sess):
    """Membership is lazy-insensitive, so RLike keeps a*?b on device;
    \\A and \\z compile as input anchors."""
    import re
    df, t = str_df(sess)
    for pat in (r"a*?b", r"o+?", r"\Aab", r"ab\z", r"\Ax.*\z"):
        q = df.select(df.u, F.rlike(df.s, pat).alias("m"))
        assert "cannot run" not in sess.explain(q), pat
        got = {r["u"]: r["m"] for r in q.collect().to_pylist()}
        for u, s in zip(t["u"].to_pylist(), t["s"].to_pylist()):
            if s is None:
                continue
            pyre = pat.replace(r"\A", "^").replace(r"\z", "$")
            exp = re.search(pyre, s) is not None
            assert got[u] == exp, (pat, s, got[u], exp)


@pytest.mark.parametrize("pat,rep", [
    (r"\d+", "#"), (r"o", "0"), (r"\s+", "_"), (r"[aeiou]", ""),
    (r"z*y", "Q"),
])
def test_regexp_replace(sess, pat, rep):
    df, t = str_df(sess)
    out = run_both(df.select(
        df.u, F.regexp_replace(df.s, pat, rep).alias("r"))).to_pylist()
    exp = [pyre.sub(pat, rep, s) for s in STRS]
    assert [r["r"] for r in out] == exp, (pat, rep)


def test_regexp_replace_group_ref_host(sess):
    df, t = str_df(sess)
    q = df.select(df.u,
                  F.regexp_replace(df.s, r"(\d)", "[$1]").alias("r"))
    assert "cannot run on TPU" in sess.explain(q)
    out = run_both(q).to_pylist()
    exp = [pyre.sub(r"(\d)", r"[\1]", s) for s in STRS]
    assert [r["r"] for r in out] == exp


def test_regexp_extract(sess):
    df, t = str_df(sess)
    out = run_both(df.select(
        df.u,
        F.regexp_extract(df.s, r"\d+", 0).alias("whole"),
        F.regexp_extract(df.s, r"(\d+)", 1).alias("g1"),
        F.regexp_extract(df.s, r"(\w+)@(\w+)", 2).alias("g2"),
    )).to_pylist()
    for r, s in zip(out, STRS):
        m = pyre.search(r"\d+", s)
        assert r["whole"] == (m.group(0) if m else "")
        assert r["g1"] == (m.group(0) if m else "")
        m2 = pyre.search(r"(\w+)@(\w+)", s)
        assert r["g2"] == (m2.group(2) if m2 else "")


def test_regexp_extract_all(sess):
    df, t = str_df(sess)
    out = run_both(df.select(
        df.u, F.regexp_extract_all(df.s, r"(\d+)", 1).alias("all")
    )).to_pylist()
    exp = [pyre.findall(r"(\d+)", s) for s in STRS]
    assert [r["all"] for r in out] == exp


def test_split(sess):
    df, t = str_df(sess)
    out = run_both(df.select(
        df.u, F.split(df.s, ",").alias("parts"),
        F.split(df.s, r"\s+").alias("ws"),
        F.split(df.s, ",", 2).alias("lim"),
    )).to_pylist()
    for r, s in zip(out, STRS):
        assert r["parts"] == s.split(","), s
        assert r["ws"] == pyre.split(r"\s+", s), s
        assert r["lim"] == s.split(",", 1), s


def test_split_device_placement(sess):
    df, t = str_df(sess)
    q = df.select(df.u, F.split(df.s, ",").alias("p"))
    assert "cannot run" not in sess.explain(q)


def test_str_to_map(sess):
    t = pa.table({"u": [0, 1, 2],
                  "s": ["a:1,b:2", "x:9", "novalue"]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(df.u, F.str_to_map(df.s).alias("m"))).to_pylist()
    assert dict(out[0]["m"]) == {"a": "1", "b": "2"}
    assert dict(out[1]["m"]) == {"x": "9"}
    assert dict(out[2]["m"]) == {"novalue": None}


def test_split_then_explode(sess):
    """regex split composes with explode downstream on the device."""
    t = pa.table({"u": [0, 1], "s": ["a,b,c", "x,y"]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u, F.explode(F.split(df.s, ",")).alias("part"))).to_pylist()
    assert [r["part"] for r in out] == ["a", "b", "c", "x", "y"]


def test_dfa_rejects_state_explosion():
    with pytest.raises(RegexUnsupported):
        # classic exponential-DFA pattern
        compile_regex("(a|b)*a(a|b){15}")


# --- JSON expressions (host-exact family) ----------------------------------

def test_get_json_object(sess):
    t = pa.table({"u": [0, 1, 2, 3],
                  "j": ['{"a": {"b": [1, 2, 3]}, "s": "hi"}',
                        '{"a": 5}', 'not json', None]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u,
        F.get_json_object(df.j, "$.a.b[1]").alias("ab1"),
        F.get_json_object(df.j, "$.s").alias("s"),
        F.get_json_object(df.j, "$.a").alias("a"),
        F.get_json_object(df.j, "$.missing").alias("mi"),
    )).to_pylist()
    assert out[0]["ab1"] == "2"
    assert out[0]["s"] == "hi"
    assert out[0]["a"] == '{"b":[1,2,3]}'
    assert out[0]["mi"] is None
    assert out[1]["a"] == "5"
    assert out[2]["ab1"] is None and out[3]["ab1"] is None


def test_json_tuple(sess):
    t = pa.table({"u": [0, 1], "j": ['{"k1": "v1", "k2": 7}', '{"k2": null}']})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u, F.json_tuple(df.j, "k1", "k2").alias("t"))).to_pylist()
    assert out[0]["t"] == {"c0": "v1", "c1": "7"}
    assert out[1]["t"] == {"c0": None, "c1": None}


def test_from_json_to_json(sess):
    import spark_rapids_tpu.types as T
    t = pa.table({"u": [0, 1, 2],
                  "j": ['{"x": 1, "y": "a", "zs": [1, 2]}',
                        '{"x": 2}', 'bad']})
    df = sess.create_dataframe(t)
    schema = T.StructType((T.StructField("x", T.LONG, True),
                           T.StructField("y", T.STRING, True),
                           T.StructField("zs", T.ArrayType(T.LONG), True)))
    q = df.select(df.u, F.from_json(df.j, schema).alias("st"))
    out = run_both(q).to_pylist()
    assert out[0]["st"] == {"x": 1, "y": "a", "zs": [1, 2]}
    assert out[1]["st"]["x"] == 2 and out[1]["st"]["y"] is None
    assert out[2]["st"] is None

    q2 = q.select(q.u, F.to_json(F.col("st")).alias("back"))
    out2 = run_both(q2).to_pylist()
    assert out2[0]["back"] == '{"x":1,"y":"a","zs":[1,2]}'


def test_split_limit_zero_java_semantics(sess):
    t = pa.table({"u": [0, 1, 2, 3], "s": ["a,b,,", ",,", "", "a,b"]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(df.u, F.split(df.s, ",", 0).alias("p"))
                   ).to_pylist()
    assert [r["p"] for r in out] == [["a", "b"], [], [""], ["a", "b"]]


def test_regexp_replace_empty_match_no_truncation(sess):
    t = pa.table({"u": [0], "s": ["bbbbbbbb"]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u, F.regexp_replace(df.s, "z*", "Q").alias("r"))).to_pylist()
    assert out[0]["r"] == "".join("Q" + ch for ch in "bbbbbbbb") + "Q"


def test_negated_class_matches_nul_byte(sess):
    t = pa.table({"u": [0], "s": ["a\x00b"]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u, F.rlike(df.s, "a[^x]b").alias("m"))).to_pylist()
    assert out[0]["m"] is True


def test_malformed_counted_brace_falls_back():
    with pytest.raises(RegexUnsupported):
        compile_regex("a{-1}")
    with pytest.raises(RegexUnsupported):
        compile_regex("a{3,1}")


def test_alternation_extent_divergence_falls_back(sess):
    """ADVICE r1 (medium): 'a|ab' is leftmost-first in Java ('a') but
    leftmost-longest in the DFA ('ab') — replace/extract/split must fall
    back to the host so results match Spark."""
    t = pa.table({"u": [0, 1, 2], "s": ["ab", "aab", "b"]})
    df = sess.create_dataframe(t)
    q = df.select(df.u, F.regexp_replace(df.s, r"a|ab", "X").alias("r"))
    assert "cannot run on TPU" in sess.explain(q)
    out = run_both(q).to_pylist()
    # Java/Python leftmost-first: 'ab' -> 'Xb'
    assert [r["r"] for r in out] == ["Xb", "XXb", "b"]


def test_alternation_same_length_stays_on_device(sess):
    df, t = str_df(sess)
    q = df.select(df.u, F.regexp_replace(df.s, r"foo|dot", "X").alias("r"))
    assert "cannot run" not in sess.explain(q)
    out = run_both(q).to_pylist()
    exp = [pyre.sub(r"foo|dot", "X", s) for s in STRS]
    assert [r["r"] for r in out] == exp


def test_rlike_alternation_still_on_device(sess):
    """Boolean search is extent-insensitive: 'a|ab' stays on device."""
    df, t = str_df(sess)
    q = df.select(df.u, F.rlike(df.s, r"a|ab").alias("m"))
    assert "cannot run" not in sess.explain(q)
    out = run_both(q).to_pylist()
    assert [r["m"] for r in out] == [bool(pyre.search(r"a|ab", s))
                                     for s in STRS]


def test_variable_alternation_split_falls_back(sess):
    t = pa.table({"u": [0, 1], "s": ["xaby", "xay"]})
    df = sess.create_dataframe(t)
    q = df.select(df.u, F.split(df.s, r"a|ab").alias("p"))
    assert "cannot run on TPU" in sess.explain(q)
    out = run_both(q).to_pylist()
    assert out[0]["p"] == pyre.compile(r"a|ab").split("xaby")
