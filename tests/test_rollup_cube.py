"""rollup/cube grouping sets — Spark lowers these to Expand + one
aggregate keyed by (keys..., grouping_id); the reference accelerates the
Expand (GpuExpandExec.scala) and the aggregate.  Oracle: pandas per-level
group-bys."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


@pytest.fixture()
def data():
    rng = np.random.default_rng(21)
    n = 10_000
    return pa.table({"a": rng.integers(0, 3, n),
                     "b": rng.integers(0, 4, n),
                     "v": rng.random(n)})


def _levels(t):
    pdf = t.to_pandas()
    l0 = pdf.groupby(["a", "b"]).agg(sv=("v", "sum")).reset_index()
    l1 = pdf.groupby(["a"]).agg(sv=("v", "sum")).reset_index()
    return pdf, l0, l1


def test_rollup_dataframe(sess, data):
    pdf, l0, l1 = _levels(data)
    got = (sess.create_dataframe(data).rollup("a", "b")
           .agg(F.sum(F.col("v")).alias("sv"),
                F.grouping_id().alias("gid"),
                F.grouping(F.col("b")).alias("gb"))
           .orderBy("gid", "a", "b").collect().to_pandas())
    assert len(got) == len(l0) + len(l1) + 1
    g0 = got[got.gid == 0]
    assert np.allclose(sorted(g0["sv"]), sorted(l0["sv"]))
    assert g0["gb"].eq(0).all()
    g1 = got[got.gid == 1]
    assert np.allclose(sorted(g1["sv"]), sorted(l1["sv"]))
    assert g1["b"].isna().all() and g1["gb"].eq(1).all()
    g3 = got[got.gid == 3]
    assert len(g3) == 1 and np.isclose(g3["sv"].iloc[0], pdf.v.sum())


def test_rollup_distinguishes_real_null_keys(sess, data):
    """A genuinely-NULL key value must not merge with the rollup total."""
    t = pa.table({"a": pa.array([1, 1, None, None], type=pa.int64()),
                  "v": [1.0, 2.0, 4.0, 8.0]})
    got = (sess.create_dataframe(t).rollup("a")
           .agg(F.sum(F.col("v")).alias("sv"),
                F.grouping_id().alias("gid"))
           .orderBy("gid", "a").collect().to_pandas())
    # levels: (a=1: 3), (a=NULL: 12), (total: 15)
    assert len(got) == 3
    fine = got[got.gid == 0]
    assert sorted(fine["sv"]) == [3.0, 12.0]
    assert float(got[got.gid == 1]["sv"].iloc[0]) == 15.0


def test_cube_dataframe(sess, data):
    pdf, l0, l1 = _levels(data)
    got = (sess.create_dataframe(data).cube("a", "b")
           .agg(F.count("*").alias("c")).collect().to_pandas())
    assert len(got) == len(l0) + len(l1) + pdf.b.nunique() + 1
    assert got["c"].sum() == 4 * len(pdf)


def test_rollup_sql(sess, data):
    pdf, l0, l1 = _levels(data)
    sess.create_dataframe(data).createOrReplaceTempView("t_rollup")
    got = sess.sql(
        "SELECT a, b, sum(v) AS sv FROM t_rollup "
        "GROUP BY ROLLUP(a, b) ORDER BY a, b").collect().to_pandas()
    assert len(got) == len(l0) + len(l1) + 1
    tot = got[got.a.isna() & got.b.isna()]
    assert np.isclose(tot["sv"].iloc[0], pdf.v.sum())
    sub = got[got.a.notna() & got.b.isna()].sort_values("a")
    assert np.allclose(sub["sv"], l1.sort_values("a")["sv"])


def test_cube_sql_with_having(sess, data):
    pdf, l0, l1 = _levels(data)
    sess.create_dataframe(data).createOrReplaceTempView("t_cube")
    got = sess.sql(
        "SELECT a, b, count(*) AS c FROM t_cube "
        "GROUP BY CUBE(a, b) HAVING count(*) > 0").collect()
    assert got.num_rows == len(l0) + len(l1) + pdf.b.nunique() + 1


def test_sql_grouping_markers(sess, data):
    """grouping_id()/grouping() resolve in the SQL ROLLUP path too."""
    pdf = data.to_pandas()
    sess.create_dataframe(data).createOrReplaceTempView("t_gmark")
    got = sess.sql(
        "SELECT a, grouping_id() AS gid, grouping(a) AS ga, sum(v) AS sv "
        "FROM t_gmark GROUP BY ROLLUP(a) ORDER BY gid, a"
    ).collect().to_pandas()
    assert got[got.gid == 0]["ga"].eq(0).all()
    tot = got[got.gid == 1]
    assert len(tot) == 1 and tot["ga"].iloc[0] == 1
    assert np.isclose(tot["sv"].iloc[0], pdf.v.sum())


def test_grouping_sets_reject_non_agg_consumers(sess, data):
    df = sess.create_dataframe(data)
    for call in (lambda g: g.applyInPandas(lambda p: p, "a long"),
                 lambda g: g.pivot("b"),
                 lambda g: g.cogroup(df.groupBy("a"))):
        with pytest.raises(ValueError, match="rollup/cube"):
            call(df.rollup("a"))


def test_sql_grouping_sets_explicit(sess, data):
    """GROUP BY GROUPING SETS ((a,b),(a),()) — explicit set list."""
    pdf = data.to_pandas()
    sess.create_dataframe(data).createOrReplaceTempView("t_gsets")
    got = sess.sql(
        "SELECT a, b, sum(v) AS sv FROM t_gsets "
        "GROUP BY GROUPING SETS ((a, b), (a), ()) ORDER BY a, b"
    ).collect().to_pandas()
    l0 = pdf.groupby(["a", "b"]).agg(sv=("v", "sum")).reset_index()
    l1 = pdf.groupby(["a"]).agg(sv=("v", "sum")).reset_index()
    assert len(got) == len(l0) + len(l1) + 1
    tot = got[got.a.isna() & got.b.isna()]
    assert np.isclose(tot["sv"].iloc[0], pdf.v.sum())


def test_sql_grouping_sets_partial(sess, data):
    """Sets that never group by the full tuple: ((a),(b))."""
    pdf = data.to_pandas()
    sess.create_dataframe(data).createOrReplaceTempView("t_gsets2")
    got = sess.sql(
        "SELECT a, b, count(*) AS c FROM t_gsets2 "
        "GROUP BY GROUPING SETS ((a), (b))").collect().to_pandas()
    assert len(got) == pdf.a.nunique() + pdf.b.nunique()
    assert got["c"].sum() == 2 * len(pdf)


def test_sql_grouping_sets_spark_semantics(sess, data):
    """Duplicate sets produce duplicate rows (correct values, not doubled);
    bare single-key elements and ordinals parse; base keys mix with a
    construct (GROUP BY a, ROLLUP(b))."""
    pdf = data.to_pandas()
    sess.create_dataframe(data).createOrReplaceTempView("t_sem")
    dup = sess.sql("SELECT a, sum(v) AS sv FROM t_sem "
                   "GROUP BY GROUPING SETS ((a), (a))").collect().to_pandas()
    l1 = pdf.groupby("a").agg(sv=("v", "sum")).reset_index()
    assert len(dup) == 2 * len(l1)
    assert np.allclose(sorted(dup["sv"]), sorted(list(l1["sv"]) * 2))

    bare = sess.sql("SELECT a, b, count(*) AS c FROM t_sem "
                    "GROUP BY GROUPING SETS (a, (a, b), ())"
                    ).collect().to_pandas()
    assert len(bare) == pdf.a.nunique() + len(pdf.groupby(["a", "b"])) + 1

    mixed = sess.sql("SELECT a, b, sum(v) AS sv FROM t_sem "
                     "GROUP BY a, ROLLUP(b) ORDER BY a, b"
                     ).collect().to_pandas()
    l0 = pdf.groupby(["a", "b"]).agg(sv=("v", "sum")).reset_index()
    assert len(mixed) == len(l0) + len(l1)
    suba = mixed[mixed.b.isna()].sort_values("a")
    assert np.allclose(suba["sv"], l1.sort_values("a")["sv"])

    ordn = sess.sql("SELECT a, count(*) AS c FROM t_sem "
                    "GROUP BY GROUPING SETS ((1), ())").collect()
    assert ordn.num_rows == pdf.a.nunique() + 1


def test_na_subset_accepts_bare_string(sess, data):
    df = sess.create_dataframe(data)
    pdf = data.to_pandas()
    assert df.na.drop(subset="v").count() == int(pdf.v.notna().sum())
    assert df.fillna(0.0, subset="v").filter(
        F.col("v").isNull()).count() == 0


def test_unpivot_accepts_column_values(sess, data):
    df = sess.create_dataframe(data)
    up = df.unpivot(["a"], [F.col("b"), F.col("v")]).collect().to_pandas()
    assert set(up["variable"]) == {"b", "v"}
