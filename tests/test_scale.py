"""Scale-test suite (reference QuerySpecs q1-q38 model + datagen rig,
SURVEY §4 tier 4) at CI size; crank SRT_SCALE_ROWS for a perf rig."""

import os

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.testing.scaletest import QUERIES, build_tables, run_suite

ROWS = int(os.environ.get("SRT_SCALE_ROWS", "30000"))


@pytest.fixture(scope="module")
def tables():
    return build_tables(ROWS)


@pytest.fixture(scope="module")
def sess():
    return srt.session()


@pytest.mark.parametrize("name", [n for n, _ in QUERIES])
def test_scale_query(name, tables, sess):
    report = run_suite(ROWS, queries={name}, tables=tables, sess=sess)
    assert report and report[0]["query"] == name
