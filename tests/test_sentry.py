"""Perf sentry (ISSUE 18): cancellable probe classification, the
append-only evidence ledger (srt-ledger/1) with torn-line safety,
live-over-stale baseline resolution (bench_diff --ledger), simulated
window open/close through the full probe -> bench -> diff -> ledger
cycle, leak-free daemon lifecycle, the /sentry telemetry route contract
(srt-sentry/1), and machine-named doctor follow-ups with quantified
lever evidence for every verdict kind."""

import importlib.util
import json
import os
import sys
import threading
import time

import pytest

from spark_rapids_tpu.observability import doctor as OD
from spark_rapids_tpu.observability import sentry as S
from spark_rapids_tpu.observability.metrics import get_registry
from spark_rapids_tpu.observability.server import TelemetryServer
from spark_rapids_tpu.serving import lifecycle as lc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_trace  # noqa: E402


def _bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "tools", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _live_artifact(tmp_path, name, value=1000.0):
    p = tmp_path / name
    p.write_text(json.dumps({
        "metric": "sentry_shape_set", "value": value, "unit": "rows/s",
        "rows": 10, "platform": "axon", "evidence": "live"}))
    return str(p)


# ---------------------------------------------------------------------------
# probe classification (cancellable, bounded timeout, QueryContext drain)
# ---------------------------------------------------------------------------

def test_device_probe_outcomes_and_context_drain():
    # default op on this CPU host: answers, but on the cpu platform
    att = S.device_probe(timeout_s=20.0)
    assert att["outcome"] == "degraded"
    assert att.get("platform") == "cpu"
    assert att["elapsed_ms"] >= 0

    # an op that raises classifies as refused, with the error banked
    def boom():
        raise RuntimeError("tunnel said no")
    att = S.device_probe(timeout_s=5.0, op=boom)
    assert att["outcome"] == "refused"
    assert "tunnel said no" in att["error"]

    # a wedged op hits the QueryContext deadline -> timeout, bounded
    # (the wedged daemon thread is abandoned, not joined — keep its
    # sleep short so it drains before the lifecycle leak test below)
    t0 = time.perf_counter()
    att = S.device_probe(timeout_s=0.3, op=lambda: time.sleep(2))
    assert att["outcome"] == "timeout"
    assert time.perf_counter() - t0 < 1.5  # bounded, not 2s

    # a healthy non-cpu op is ok
    att = S.device_probe(timeout_s=5.0, op=lambda: "axon")
    assert att["outcome"] == "ok"
    assert att["platform"] == "axon"

    # every probe context unregistered, even the cancelled/timed-out one
    assert not [q for q in lc.live_queries()
                if q.session_id == "sentry"]


# ---------------------------------------------------------------------------
# evidence ledger: schema round-trip, append-only, torn-line safety
# ---------------------------------------------------------------------------

def test_ledger_round_trip_append_only_and_torn_line(tmp_path):
    led = S.EvidenceLedger(str(tmp_path / "ledger.jsonl"))
    assert led.entries() == [] and led.last_live() is None
    r1 = led.append({"evidence": "live", "artifact": "/a.json"})
    assert r1["schema"] == S.LEDGER_SCHEMA and r1["at"] and r1["unix"]
    first_line = open(led.path).readline()
    led.append({"evidence": "stale-replay", "artifact": "/b.json"})
    # append-only: the first record's bytes are untouched by the second
    assert open(led.path).readline() == first_line
    assert [e["artifact"] for e in led.entries()] == ["/a.json", "/b.json"]

    # torn trailing line (crash mid-append) and foreign lines are
    # skipped on read, never fatal, and never hide banked history
    with open(led.path, "a") as fh:
        fh.write("not json\n")
        fh.write('{"schema": "other/1", "evidence": "live"}\n')
        fh.write('{"schema": "srt-ledger/1", "evidence": "l')
    assert len(led.entries()) == 2
    assert led.tail(1)[0]["artifact"] == "/b.json"

    # last_live picks the newest LIVE entry, not the newest entry
    assert led.last_live()["artifact"] == "/a.json"
    age = led.last_live_age_s()
    assert age is not None and 0.0 <= age < 60.0


# ---------------------------------------------------------------------------
# baseline resolution: live-over-stale, refusal semantics, exit codes
# ---------------------------------------------------------------------------

def test_resolve_baseline_live_over_stale(tmp_path):
    bd = _bench_diff()
    led = S.EvidenceLedger(str(tmp_path / "ledger.jsonl"))
    led.append({"evidence": "live", "artifact": "/old_live.json"})
    led.append({"evidence": "live", "artifact": "/new_live.json"})
    led.append({"evidence": "stale-replay", "artifact": "/newest.json"})
    entries = bd.read_ledger(led.path)
    assert len(entries) == 3
    # the newest LIVE entry wins even though a stale one is newer
    assert bd.resolve_baseline(entries) == "/new_live.json"
    # no live entries: None without allow_stale, newest-any with it
    stale_only = [e for e in entries if e["evidence"] != "live"]
    assert bd.resolve_baseline(stale_only) is None
    assert bd.resolve_baseline(stale_only,
                               allow_stale=True) == "/newest.json"


def test_bench_diff_ledger_cli_exit_codes(tmp_path):
    bd = _bench_diff()
    base = _live_artifact(tmp_path, "base.json", 1000.0)
    fresh_ok = _live_artifact(tmp_path, "fresh.json", 1001.0)
    regressed = _live_artifact(tmp_path, "regressed.json", 500.0)
    led = S.EvidenceLedger(str(tmp_path / "ledger.jsonl"))
    led.append({"evidence": "live", "artifact": base})
    # auto-resolved live baseline, within threshold
    assert bd.main(["--ledger", led.path, fresh_ok]) == 0
    # --fail-on-regress keeps its exit-3 contract through ledger mode
    assert bd.main(["--ledger", led.path, regressed,
                    "--fail-on-regress"]) == 3
    # a ledger with no live entry refuses (exit 2) without --allow-stale
    stale = S.EvidenceLedger(str(tmp_path / "stale.jsonl"))
    stale.append({"evidence": "stale-replay", "artifact": base})
    assert bd.main(["--ledger", stale.path, fresh_ok]) == 2
    assert bd.main(["--ledger", stale.path, fresh_ok,
                    "--allow-stale"]) == 0


# ---------------------------------------------------------------------------
# window open/close through the full cycle, with fakes
# ---------------------------------------------------------------------------

def _fake_bench(value):
    def fn(shapes):
        return {"metric": "sentry_shape_set", "value": value,
                "unit": "rows/s", "rows": 10, "platform": "axon",
                "evidence": "live", "shapes": list(shapes),
                "extra_metrics": {"join_trace_summary": {
                    "sync_count": 4, "sync_ms": 80.0,
                    "compile_count": 1, "compile_ms": 5.0}}}
    return fn


def test_window_open_close_backoff_and_ledger_cycle(tmp_path):
    outcomes = iter(["refused", "timeout", "ok", "ok"])

    def probe():
        o = next(outcomes)
        return {"outcome": o, "elapsed_ms": 1.0,
                **({"platform": "axon"} if o == "ok" else {})}

    s = S.PerfSentry(probe=probe, bench=_fake_bench(1000.0),
                     ledger=str(tmp_path / "ledger.jsonl"),
                     shapes=["join"], interval_s=10.0)
    # closed window: no entry, exponential backoff from the interval
    assert s.run_once() is None
    assert s.backoff_s == 10.0  # first failure: base interval
    assert s.run_once() is None
    assert s.backoff_s == 20.0  # second failure doubles
    assert s.ledger.entries() == [] and s.windows == 0

    # window opens: full probe -> bench -> diff -> ledger cycle
    e1 = s.run_once()
    assert e1 is not None and s.windows == 1
    assert s.backoff_s == 10.0  # success resets the backoff
    assert e1["evidence"] == "live"
    assert os.path.exists(e1["artifact"])
    assert e1["diff"]["verdict"] == "no-baseline"
    assert e1["probe"]["outcome"] == "ok"
    assert e1["doctor"]["verdict"] == "sync-bound"
    assert e1["followup"].startswith("sync-bound:")

    # second window diffs against the first's artifact (auto-resolved
    # live baseline from the ledger)
    s._bench = _fake_bench(2000.0)
    e2 = s.run_once()
    assert e2["diff"]["baseline"] == e1["artifact"]
    assert e2["diff"]["verdict"] == "ok"
    assert e2["diff"]["improved"] >= 1  # value 1000 -> 2000
    assert len(s.ledger.entries()) == 2
    # per-attempt probe telemetry banked with outcomes classified
    st = s.status()
    assert st["probe"]["outcomes"] == {"refused": 1, "timeout": 1,
                                       "ok": 2}


def test_sentry_thread_lifecycle_is_leak_free(tmp_path):
    s = S.PerfSentry(probe=lambda: {"outcome": "refused",
                                    "elapsed_ms": 0.1},
                     bench=_fake_bench(1.0),
                     ledger=str(tmp_path / "ledger.jsonl"),
                     interval_s=0.05)
    s.start()
    assert s.running
    assert S.get_active() is s  # /sentry route now serves this sentry
    assert any(t.name == "srt-sentry" for t in threading.enumerate())
    time.sleep(0.2)
    s.stop(timeout=10.0)
    assert not s.running
    assert S.get_active() is None
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
            t.name.startswith("srt-sentry")
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not [t.name for t in threading.enumerate()
                if t.name.startswith("srt-sentry")]
    assert not [q for q in lc.live_queries()
                if q.session_id == "sentry"]
    assert s.phase == "stopped"
    # probe attempts were banked as registry metrics while it ran
    text = get_registry().prometheus_text()
    assert "srt_sentry_probe_attempts_total" in text


# ---------------------------------------------------------------------------
# /sentry route contract (srt-sentry/1)
# ---------------------------------------------------------------------------

def test_sentry_route_contract(tmp_path):
    import urllib.error
    import urllib.request

    s = S.PerfSentry(probe=lambda: {"outcome": "ok", "platform": "axon",
                                    "elapsed_ms": 0.5},
                     bench=_fake_bench(100.0),
                     ledger=str(tmp_path / "ledger.jsonl"),
                     shapes=["join"])
    s.run_once()
    S.set_active(s)
    srv = TelemetryServer(
        metrics_text=lambda: get_registry().prometheus_text(),
        healthz=lambda: (True, {}), queries=lambda: [],
        doctor=lambda: {}, slo=lambda: {})
    try:
        with urllib.request.urlopen(srv.endpoint + "/sentry",
                                    timeout=5) as resp:
            assert resp.status == 200
            doc = json.loads(resp.read().decode())
        assert doc["schema"] == "srt-sentry/1"
        assert doc["phase"] in check_trace.SENTRY_PHASES
        assert doc["windows"] == 1
        assert doc["probe"]["last"]["outcome"] == "ok"
        assert doc["ledger"]["entries"] == 1
        assert doc["ledger"]["tail"][0]["schema"] == "srt-ledger/1"
        assert doc["last_live_age_s"] is not None
        # the CI validator accepts the payload via --endpoint
        desc = check_trace.check_endpoint(srv.endpoint + "/sentry")
        assert desc.startswith("sentry phase ")
        assert check_trace.main(
            ["--endpoint", srv.endpoint + "/sentry"]) == 0
        # 404 names /sentry among the known routes
        try:
            urllib.request.urlopen(srv.endpoint + "/nope", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert "/sentry" in json.loads(e.read().decode())["routes"]
    finally:
        srv.close()
        S.set_active(None)

    # with no active sentry the payload degrades honestly but keeps the
    # schema and ledger staleness visible
    none = S.status_payload()
    assert none["schema"] == "srt-sentry/1" and none["phase"] == "none"
    assert check_trace.check_sentry(none).startswith("sentry phase none")

    # a malformed payload is rejected by the validator
    with pytest.raises(ValueError):
        check_trace.check_sentry({"schema": "srt-sentry/1",
                                  "phase": "bogus"})


# ---------------------------------------------------------------------------
# doctor: quantified lever evidence + stale-evidence refusal
# ---------------------------------------------------------------------------

def test_followup_naming_quantified_for_multiple_verdicts():
    sync = OD.diagnose_summary({"sync_count": 18, "sync_ms": 120.0,
                                "compile_count": 1, "compile_ms": 2.0})
    assert sync["verdict"] == "sync-bound"
    f = OD.followup(sync)
    assert f.startswith("sync-bound: ")
    assert "readbacks=18" in f and "ms_per_readback=" in f
    assert "; lever: " in f

    comp = OD.diagnose_summary({"sync_count": 1, "sync_ms": 1.0,
                                "compile_count": 5, "compile_ms": 900.0})
    assert comp["verdict"] == "compile-bound"
    f = OD.followup(comp)
    assert f.startswith("compile-bound: ")
    assert "compiles=5" in f and "ms_per_compile=180" in f
    assert "; lever: " in f

    # EVERY verdict kind has a named lever (the dispatch-bound precision
    # is the floor, not the ceiling)
    for kind in OD.VERDICTS:
        assert kind == "no-bottleneck" or kind in OD.LEVERS


def test_stale_evidence_stamps_age_and_refuses_followup():
    diag = OD.diagnose_summary(
        {"sync_count": 9, "sync_ms": 50.0},
        evidence="stale-replay", evidence_age_s=7200.0)
    assert diag["evidence"] == "stale-replay"
    assert diag["evidence_age_s"] == 7200.0
    assert any("STALE-EVIDENCE" in c for c in diag.get("caveats", []))
    f = OD.followup(diag)
    assert f.startswith("STALE-EVIDENCE")
    assert "refused" in f
    # live evidence passes through to a real follow-up
    live = OD.diagnose_summary({"sync_count": 9, "sync_ms": 50.0},
                               evidence="live", evidence_age_s=1.0)
    assert OD.followup(live).startswith("sync-bound:")


def test_diagnose_artifact_derives_evidence_and_age(tmp_path):
    art = {"metric": "sentry_shape_set", "platform": "axon",
           "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(time.time() - 300)),
           "extra_metrics": {"join_trace_summary": {
               "sync_count": 3, "sync_ms": 30.0}}}
    diag = OD.diagnose_artifact(art)
    # captured_at marks a replay: evidence derived, age stamped, and the
    # follow-up refused with the loud marker
    assert diag["evidence"] == "stale-replay"
    assert 250.0 <= diag["evidence_age_s"] <= 600.0
    assert OD.followup(diag).startswith("STALE-EVIDENCE")


# ---------------------------------------------------------------------------
# bench.run_shape_set: the callable entrypoint, real engine, tiny rows
# ---------------------------------------------------------------------------

def test_run_shape_set_real_engine_small(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_for_sentry_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = str(tmp_path / "art.json")
    art = bench.run_shape_set(["sort"], rows=4000, budget_s=120,
                              artifact_path=out, evidence="live")
    assert art["metric"] == "sentry_shape_set"
    assert art["evidence"] == "live"
    assert art["extra_metrics"]["sort_rows_per_sec"] > 0
    assert art["phases"]["shape_sort"]["timed_out"] is False
    # banked incrementally: the on-disk artifact matches
    banked = json.loads(open(out).read())
    assert banked["extra_metrics"]["sort_rows_per_sec"] \
        == art["extra_metrics"]["sort_rows_per_sec"]
    # the doctor can diagnose it end to end (the sentry's ledger step)
    diag = OD.diagnose_artifact(art)
    assert diag["verdict"] in OD.VERDICTS
    assert OD.followup(diag)  # always machine-named, never empty
