"""Multi-tenant query serving (ISSUE 9, docs/serving.md): weighted-fair
admission + per-tenant budgets, cross-query sharing tiers (result cache,
shared broadcasts, generation-safe kernel-cache clearing), per-tenant
observability (metrics labels, trace spans, shared history, doctor), and
the multi-session chaos soak — tier-1 because an admission or sharing
bug is either silent cross-tenant data corruption or silent starvation.
"""

import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.serving import (AdmissionController, AdmissionTimeout,
                                      ServingEngine, estimate_query_bytes)
from spark_rapids_tpu.serving import broadcast_cache as BC
from spark_rapids_tpu.serving import result_cache as RC
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import plan as P
from spark_rapids_tpu.sql.session import TpuSession


@pytest.fixture(autouse=True)
def _clean_shared_tiers():
    def reset():
        RC.clear()
        BC.clear()
        for d in (RC.STATS, BC.STATS):
            for k in d:
                d[k] = 0
    reset()
    yield
    reset()


def _drain(ctrl, tenants, order):
    """Enqueue one blocked waiter per (tenant, i), then release the
    blocker and let grants run one at a time; returns the grant order."""
    blocker = ctrl.acquire("blocker")
    threads = []

    def worker(tenant):
        t = ctrl.acquire(tenant)
        order.append(tenant)
        ctrl.release(t)

    for tenant in tenants:
        th = threading.Thread(target=worker, args=(tenant,))
        th.start()
        threads.append(th)
    deadline = time.time() + 10
    while ctrl.snapshot()["queued"] < len(tenants):
        assert time.time() < deadline, "waiters failed to enqueue"
        time.sleep(0.005)
    ctrl.release(blocker)
    for th in threads:
        th.join(20)
        assert not th.is_alive()


# --------------------------------------------------------------------------
# admission control
# --------------------------------------------------------------------------

def test_wfq_weighted_light_tenant_first():
    # light weight 4x heavy: light vfts (0.25, 0.5) < heavy's (1..8), so
    # both light queries admit before ANY heavy one regardless of
    # enqueue interleaving
    ctrl = AdmissionController(max_concurrent=1, weights={"light": 4.0})
    order = []
    _drain(ctrl, ["heavy"] * 8 + ["light"] * 2, order)
    assert order[:2] == ["light", "light"], order
    assert len(order) == 10


def test_wfq_equal_weights_interleave():
    # equal weights: a flood of 8 heavy requests cannot push the 2 light
    # ones to the back — vfts interleave 1:1, so both light queries are
    # admitted within the first ~2*k grants (bounded p99 admission wait,
    # the no-starvation contract)
    ctrl = AdmissionController(max_concurrent=1)
    order = []
    _drain(ctrl, ["heavy"] * 8 + ["light"] * 2, order)
    positions = [i for i, t in enumerate(order) if t == "light"]
    assert positions[0] <= 2 and positions[1] <= 4, order
    snap = ctrl.snapshot()
    assert snap["admitted"] == 11  # blocker + 10
    assert snap["per_tenant"]["light"]["wait_ms_p99"] >= 0.0


def test_admission_memory_budget_blocks_and_releases():
    ctrl = AdmissionController(max_concurrent=4,
                               budgets={"a": 100})
    t1 = ctrl.acquire("a", est_bytes=60)
    got = {}

    def second():
        got["t"] = ctrl.acquire("a", est_bytes=60)

    th = threading.Thread(target=second)
    th.start()
    th.join(0.3)
    assert th.is_alive(), "second query admitted over budget"
    # another tenant is not blocked by a's budget stall
    tb = ctrl.acquire("b", est_bytes=60)
    ctrl.release(tb)
    ctrl.release(t1)
    th.join(10)
    assert not th.is_alive()
    ctrl.release(got["t"])


def test_admission_budget_lone_oversized_query_admits():
    ctrl = AdmissionController(max_concurrent=2, budgets={"a": 100})
    t = ctrl.acquire("a", est_bytes=500)  # over budget, nothing in flight
    ctrl.release(t)


def test_admission_timeout_raises():
    ctrl = AdmissionController(max_concurrent=1)
    t = ctrl.acquire("x")
    with pytest.raises(AdmissionTimeout):
        ctrl.acquire("y", timeout_ms=60)
    ctrl.release(t)
    snap = ctrl.snapshot()
    assert snap["timeouts"] == 1 and snap["queued"] == 0


def test_estimate_query_bytes_counts_inputs(tmp_path):
    table = pa.table({"a": np.arange(1000), "b": np.arange(1000.0)})
    rel = P.Relation(table, None)
    assert estimate_query_bytes(rel) == table.nbytes
    path = str(tmp_path / "t.parquet")
    pq.write_table(table, path)
    scan = P.ScanRelation("parquet", (path,), None, {})
    assert estimate_query_bytes(scan) == os.path.getsize(path)


# --------------------------------------------------------------------------
# the serving engine end to end
# --------------------------------------------------------------------------

def _mk_tables(n=8_000, seed=7):
    rng = np.random.default_rng(seed)
    fact = pa.table({"fk": rng.integers(0, 50, n), "x": rng.random(n),
                     "q": rng.integers(0, 100, n)})
    dim = pa.table({"pk": np.arange(50, dtype=np.int64),
                    "cat": rng.integers(0, 8, 50)})
    return fact, dim


def _join_q(sess, fact_t, dim_t, thresh=50):
    fact = sess.create_dataframe(fact_t, num_partitions=2)
    dim = sess.create_dataframe(dim_t)
    return (fact.filter(F.col("q") < thresh)
            .join(dim, fact.fk == dim.pk, "inner").groupBy("cat")
            .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
            .orderBy("cat")).collect()


def test_engine_concurrent_tenants_end_to_end(tmp_path):
    fact_t, dim_t = _mk_tables()
    eng = ServingEngine(**{
        "spark.rapids.tpu.metrics.enabled": True,
        "spark.rapids.tpu.profile.enabled": True,
        "spark.rapids.tpu.serving.resultCache.enabled": True,
        "spark.rapids.tpu.serving.broadcastShare.enabled": True,
        "spark.rapids.tpu.serving.maxConcurrentQueries": 2,
    })
    try:
        results, hists = {}, {}

        def worker(tenant):
            s = eng.session(tenant=tenant)
            results[tenant] = [_join_q(s, fact_t, dim_t),
                               _join_q(s, fact_t, dim_t)]
            hists[tenant] = s.query_history()
            results[tenant + "_metrics"] = dict(s.last_query_metrics)

        threads = [threading.Thread(target=worker, args=(f"t{i}",))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert results["t0"][0].equals(results["t1"][0])
        assert results["t0"][0].equals(results["t0"][1])
        # repeats hit the result tier (either tenant may have seeded it)
        assert RC.stats()["hits"] >= 2
        # per-session history views are disjoint and tenant-stamped
        assert len(hists["t0"]) == 2 and len(hists["t1"]) == 2
        assert {r["tenant"] for r in hists["t0"]} == {"t0"}
        fleet = eng.query_history()
        assert len(fleet) == 4
        assert {r["tenant"] for r in fleet} == {"t0", "t1"}
        # admission accounting covers executed queries (cache hits
        # bypass admission by design)
        adm = eng.admission_stats()
        assert adm["admitted"] >= 2
        # per-tenant metric labels reached the registry
        prom = eng.metrics_prometheus()
        assert 'tenant="t0"' in prom and 'tenant="t1"' in prom
        assert "result_cache_served_total" in prom
        # engine-scoped trace carries tenant-stamped spans
        path = str(tmp_path / "trace.json")
        eng.export_chrome_trace(path)
        evs = json.load(open(path))["traceEvents"]
        assert any(e.get("args", {}).get("tenant") for e in evs)
        # per-tenant doctor verdicts exist for both tenants
        diag = eng.diagnose_tenants()
        assert set(diag) == {"t0", "t1"}
        for rep in diag.values():
            assert rep["queries"] == 2
            assert rep["diagnosis"]["verdict"]
    finally:
        eng.close()
    # engine close restored the process flags
    from spark_rapids_tpu.observability.metrics import METRICS
    from spark_rapids_tpu.observability.tracer import TRACING
    assert not METRICS["on"] and not TRACING["on"]


def test_engine_close_restores_chaos_arming():
    from spark_rapids_tpu.robustness.faults import CHAOS, snapshot_arming
    prev = snapshot_arming()
    eng = ServingEngine(**{
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 3,
        "spark.rapids.tpu.chaos.sites": "shuffle.fetch:0.5",
    })
    assert CHAOS["on"], "engine conf must arm chaos engine-scoped"
    eng.close()
    assert snapshot_arming()[0] == prev[0]
    from spark_rapids_tpu.robustness import disarm_chaos
    disarm_chaos()


# --------------------------------------------------------------------------
# result cache
# --------------------------------------------------------------------------

def _rc_session(**extra):
    conf = {"spark.rapids.tpu.serving.resultCache.enabled": True}
    conf.update(extra)
    return TpuSession(RapidsConf.get_global().copy(conf))


def test_result_cache_hits_in_memory_inputs():
    fact_t, dim_t = _mk_tables()
    sess = _rc_session()
    r1 = _join_q(sess, fact_t, dim_t)
    assert RC.stats()["stores"] == 1
    r2 = _join_q(sess, fact_t, dim_t)
    assert r1.equals(r2)
    assert RC.stats()["hits"] == 1
    assert sess.last_query_metrics.get("resultCacheHit") == 1
    # the hit still left a flight-recorder record
    hist = sess.query_history()
    assert len(hist) == 2
    # different literal = different entry, not a false hit
    r3 = _join_q(sess, fact_t, dim_t, thresh=30)
    assert not r3.equals(r1)
    assert RC.stats()["hits"] == 1 and RC.stats()["stores"] == 2


def test_result_cache_file_stat_invalidation(tmp_path):
    path = str(tmp_path / "f.parquet")
    pq.write_table(pa.table({"a": [1, 2, 3]}), path)
    sess = _rc_session()
    q = lambda: sess.read.parquet(path).groupBy().agg(  # noqa: E731
        F.sum(F.col("a")).alias("s")).collect()
    assert q().to_pylist() == [{"s": 6}]
    assert q().to_pylist() == [{"s": 6}]
    assert RC.stats()["hits"] == 1
    pq.write_table(pa.table({"a": [10, 20, 30, 40]}), path)
    assert q().to_pylist() == [{"s": 100}], \
        "stale cached result served after the input file changed"
    assert RC.stats()["invalidations"] >= 1


def test_result_cache_write_through_writers_invalidates(tmp_path):
    src = str(tmp_path / "src")
    sess = _rc_session()
    base = sess.create_dataframe(pa.table({"a": [1, 2, 3]}))
    base.write.parquet(src)
    q = lambda: sess.read.parquet(src).groupBy().agg(  # noqa: E731
        F.sum(F.col("a")).alias("s")).collect()
    assert q().to_pylist() == [{"s": 6}]
    assert q().to_pylist() == [{"s": 6}]
    assert RC.stats()["hits"] >= 1
    inv0 = RC.stats()["invalidations"]
    # an engine write over the scanned directory sweeps the entry
    sess.create_dataframe(pa.table({"a": [5, 5]})) \
        .write.mode("overwrite").parquet(src)
    assert RC.stats()["invalidations"] > inv0
    assert q().to_pylist() == [{"s": 10}]


def test_result_cache_declines_nondeterministic():
    sess = _rc_session()
    df = sess.range(100).withColumn("r", F.rand(seed=None)) \
        if hasattr(F, "rand") else None
    if df is None:
        pytest.skip("no rand()")
    df.agg(F.sum(F.col("r")).alias("s")).collect()
    assert RC.stats()["stores"] == 0, \
        "non-deterministic plan must not be cached"


def test_result_cache_lru_byte_bound():
    RC.set_max_bytes(1)  # below any result's nbytes
    sess = _rc_session()
    sess.create_dataframe(pa.table({"a": [1, 2]})).groupBy().agg(
        F.sum(F.col("a")).alias("s")).collect()
    assert RC.stats()["entries"] == 0  # too big to store
    RC.set_max_bytes(256 << 20)


def test_result_cache_dead_table_never_hits():
    sess = _rc_session()
    t = pa.table({"a": list(range(100))})
    sess.create_dataframe(t).groupBy().agg(
        F.sum(F.col("a")).alias("s")).collect()
    assert RC.stats()["stores"] == 1
    del t  # input table dies; id() may be recycled by a new table
    t2 = pa.table({"a": [9, 9, 9]})
    got = sess.create_dataframe(t2).groupBy().agg(
        F.sum(F.col("a")).alias("s")).collect()
    assert got.to_pylist() == [{"s": 27}]
    assert RC.stats()["hits"] == 0


# --------------------------------------------------------------------------
# shared broadcast cache
# --------------------------------------------------------------------------

def test_broadcast_share_across_sessions():
    fact_t, dim_t = _mk_tables()
    conf = {"spark.rapids.tpu.serving.broadcastShare.enabled": True}
    s1 = TpuSession(RapidsConf.get_global().copy(conf))
    s2 = TpuSession(RapidsConf.get_global().copy(conf))
    r1 = _join_q(s1, fact_t, dim_t)
    assert BC.stats()["stores"] == 1
    r2 = _join_q(s2, fact_t, dim_t, thresh=30)  # different query, same dim
    assert BC.stats()["hits"] >= 1, BC.stats()
    # parity against a share-disabled session
    s3 = TpuSession(RapidsConf.get_global())
    assert _join_q(s3, fact_t, dim_t).equals(r1)
    assert _join_q(s3, fact_t, dim_t, thresh=30).equals(r2)


def test_broadcast_share_entries_pinned():
    from spark_rapids_tpu.memory import retention
    fact_t, dim_t = _mk_tables()
    conf = {"spark.rapids.tpu.serving.broadcastShare.enabled": True}
    s1 = TpuSession(RapidsConf.get_global().copy(conf))
    _join_q(s1, fact_t, dim_t)
    ent = list(BC._ENTRIES.values())
    assert ent and retention.is_pinned(ent[0][1])
    BC.clear()
    # the cache's own pin released on clear (plan pins may remain)
    assert BC.stats()["entries"] == 0


# --------------------------------------------------------------------------
# kernel-cache clearing under concurrency (satellite 1)
# --------------------------------------------------------------------------

def test_clear_cache_bumps_generation_and_drops_stale_learning():
    from spark_rapids_tpu.sql.physical import join as PJ
    from spark_rapids_tpu.sql.physical.kernel_cache import (
        cache_generation, clear_cache)
    g0 = cache_generation()
    PJ.record_selectivity(("k",), 1.5, generation=g0)
    assert PJ.lookup_selectivity(("k",)) == 1.5
    clear_cache()
    assert cache_generation() == g0 + 1
    assert PJ.lookup_selectivity(("k",)) is None
    # a recorder that learned against the dead generation is dropped
    PJ.record_selectivity(("k",), 2.5, generation=g0)
    assert PJ.lookup_selectivity(("k",)) is None
    assert PJ.STATS.get("stale_selectivity_drops", 0) >= 1
    # a current-generation recorder lands
    PJ.record_selectivity(("k",), 2.5, generation=g0 + 1)
    assert PJ.lookup_selectivity(("k",)) == 2.5
    clear_cache()


def test_clear_cache_keeps_inflight_kernel_handles():
    from spark_rapids_tpu.sql.physical.kernel_cache import (cached_jit,
                                                            clear_cache)
    fn = cached_jit(("test_serving_inflight", 1), lambda x: x + 1)
    clear_cache()
    # the handed-out wrapper still owns its program: in-flight execution
    # survives a concurrent clear
    assert int(fn(np.int64(41))) == 42
    clear_cache()


def test_concurrent_queries_with_concurrent_clears_bit_identical():
    # hammer: 2 sessions run the same join repeatedly while a third
    # thread clears the kernel cache — results must stay correct
    fact_t, dim_t = _mk_tables(n=4_000)
    ref = _join_q(TpuSession(RapidsConf.get_global()), fact_t, dim_t)
    from spark_rapids_tpu.sql.physical.kernel_cache import clear_cache
    stop = threading.Event()
    errors = []

    def clearer():
        while not stop.is_set():
            clear_cache()
            time.sleep(0.002)

    def runner():
        try:
            s = TpuSession(RapidsConf.get_global())
            for _ in range(3):
                got = _join_q(s, fact_t, dim_t)
                assert got.equals(ref)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    cl = threading.Thread(target=clearer)
    rs = [threading.Thread(target=runner) for _ in range(2)]
    cl.start()
    for t in rs:
        t.start()
    for t in rs:
        t.join(120)
    stop.set()
    cl.join(10)
    assert not errors, errors


# --------------------------------------------------------------------------
# shared query history (satellite 2)
# --------------------------------------------------------------------------

def test_history_jsonl_shared_and_filtered(tmp_path):
    from spark_rapids_tpu.observability.history import read_history_file
    path = str(tmp_path / "hist.jsonl")
    fact_t, dim_t = _mk_tables(n=2_000)
    conf = {"spark.rapids.tpu.history.path": path,
            "spark.rapids.tpu.serving.tenant": "shared-t"}
    sessions = [TpuSession(RapidsConf.get_global().copy(conf))
                for _ in range(3)]
    # concurrent sessions share ONE history instance (and append lock)
    assert sessions[0]._history is None  # lazy until first record
    threads = [threading.Thread(
        target=lambda s=s: [_join_q(s, fact_t, dim_t) for _ in range(3)])
        for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert sessions[0]._history is sessions[1]._history is \
        sessions[2]._history
    # no torn/interleaved lines: every line parses, all records present
    recs = read_history_file(path)
    raw_lines = [ln for ln in open(path) if ln.strip()]
    assert len(raw_lines) == len(recs) == 9
    assert all(r.get("tenant") == "shared-t" for r in recs)
    # per-session filtering over the shared ring
    for s in sessions:
        mine = s.query_history()
        assert len(mine) == 3
        assert {r["session"] for r in mine} == {s.session_id}


# --------------------------------------------------------------------------
# per-tenant doctor
# --------------------------------------------------------------------------

def test_diagnose_tenants_ranks_admission_wait():
    from spark_rapids_tpu.observability.doctor import diagnose_tenants
    recs = [
        {"tenant": "a", "status": "ok", "duration_ms": 10.0,
         "metrics": {"admissionWaitMs": 500.0},
         "trace_summary": {"sync_ms": 1.0, "sync_count": 1}},
        {"tenant": "b", "status": "ok", "duration_ms": 50.0,
         "metrics": {},
         "trace_summary": {"sync_ms": 40.0, "sync_count": 4}},
    ]
    out = diagnose_tenants(recs)
    assert out["a"]["diagnosis"]["verdict"] == "admission-bound"
    assert out["b"]["diagnosis"]["verdict"] == "sync-bound"
    assert out["a"]["admission_wait_ms"] == 500.0
    assert out["a"]["p50_ms"] == 10.0


# --------------------------------------------------------------------------
# multi-session chaos soak (satellite 3, reduced tier-1 variant)
# --------------------------------------------------------------------------

def test_multi_session_chaos_soak_small():
    from spark_rapids_tpu.testing.chaos import run_multi_session_soak
    report = run_multi_session_soak(
        rows=4_000, seed=11, tenants=2,
        queries=["agg", "join_agg", "ooc_sort"])
    assert report["bit_identical"]
    assert report["faults_injected"] > 0
    assert report["history_per_tenant"] == {"tenant0": 3, "tenant1": 3}
    assert report["admission"]["admitted"] == 6
