"""Shim-axis proof (VERDICT r3 #8): BOTH jax ShimProviders load and
serve the SAME engine code end-to-end — the reference's parallel-world
property (``ShimLoader.scala:46-76``), where one artifact works across
its whole compatibility axis.

The installed jax still ships the legacy entry points
(``jax.tree_util.*``, experimental/top-level ``shard_map``), so the
legacy provider is genuinely exercisable here: these tests force each
provider in turn (provider injection, the test-time analog of running
under an old jaxlib) and drive real engine work through every shimmed
entry point — batch pytrees (tree_map/flatten/unflatten ride every
collect via columnar/convert and collect_fusion) and the mesh
``shard_map`` data plane."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import shims
from spark_rapids_tpu.sql import functions as F


@pytest.fixture(params=["JaxModernShim", "JaxLegacyShim"])
def forced_shim(request):
    """Force one provider, restore afterwards."""
    cls = {c.__name__: c for c in shims.PROVIDERS}[request.param]
    old = shims._active
    shims._active = cls()
    try:
        yield cls
    finally:
        shims._active = old


def _shard_map_or_skip(provider):
    """The provider's shard_map entry point, or skip — the same
    availability skip tests/test_shuffle.py uses: the installed jax may
    not expose the FORCED provider's entry point (e.g. jax 0.4.x has no
    top-level ``jax.shard_map`` for JaxModernShim), and tier-1 must be
    green-or-skip on such environments."""
    try:
        return provider.shard_map()
    except (ImportError, AttributeError):
        pytest.skip("this provider's shard_map entry point is "
                    "unavailable in this environment")


def test_provider_probing_matches_versions():
    assert shims.JaxModernShim.matches((0, 6, 0))
    assert shims.JaxModernShim.matches((0, 9, 0))
    assert not shims.JaxModernShim.matches((0, 5, 3))
    assert shims.JaxLegacyShim.matches((0, 4, 30))
    assert shims.JaxLegacyShim.matches((0, 5, 3))
    assert not shims.JaxLegacyShim.matches((0, 6, 0))
    # the running jax resolves to exactly one provider
    v = shims._jax_version()
    assert sum(c.matches(v) for c in shims.PROVIDERS) == 1


def test_both_providers_supply_working_apis(forced_shim):
    """Each provider's four entry points work against the installed
    jax (the legacy surface still exists in modern jax)."""
    s = shims.get_shim()
    assert type(s) is forced_shim
    tree = {"a": np.arange(3), "b": (np.ones(2),)}
    doubled = shims.tree_map(lambda x: x * 2, tree)
    assert doubled["a"][2] == 4 and doubled["b"][0][1] == 2.0
    leaves, treedef = shims.tree_flatten(tree)
    assert len(leaves) == 2
    back = shims.tree_unflatten(treedef, leaves)
    assert np.array_equal(back["a"], tree["a"])
    assert callable(_shard_map_or_skip(s))


def test_engine_query_end_to_end_under_each_provider(forced_shim):
    """A real query (filter + join + agg + sort -> collect) runs through
    the forced provider: batch pytrees traverse tree_flatten/unflatten
    in the packed D2H fetch, tree_map in transitions — the quick-tier
    slice of the engine on BOTH shim worlds."""
    sess = srt.session()
    rng = np.random.default_rng(1)
    fact = pa.table({"k": rng.integers(0, 50, 20_000),
                     "v": rng.random(20_000)})
    dim = pa.table({"k": np.arange(50, dtype=np.int64),
                    "w": rng.random(50)})
    f = sess.create_dataframe(fact, num_partitions=3)
    d = sess.create_dataframe(dim, num_partitions=2)
    got = (f.filter(f.v > 0.25).join(d, on="k", how="inner")
           .groupBy("k").agg(F.sum(F.col("v")).alias("sv"),
                             F.count("*").alias("c"))
           .orderBy("k").collect().to_pandas())
    fp, dp = fact.to_pandas(), dim.to_pandas()
    m = fp[fp.v > 0.25].merge(dp, on="k")
    exp = (m.groupby("k").agg(sv=("v", "sum"), c=("v", "size"))
           .sort_index().reset_index())
    assert np.array_equal(got["k"], exp["k"])
    assert np.array_equal(got["c"], exp["c"])
    assert np.allclose(got["sv"], exp["sv"])


def test_mesh_shard_map_under_each_provider(forced_shim):
    """The ICI mesh data plane compiles and runs through the forced
    provider's shard_map on the 8-device virtual mesh."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    from spark_rapids_tpu.parallel.mesh import device_mesh
    from spark_rapids_tpu.shims import get_shim
    from jax.sharding import PartitionSpec as P
    mesh = device_mesh(len(jax.devices()))
    if mesh is None:
        pytest.skip("no mesh available")
    sm = _shard_map_or_skip(get_shim())
    import jax.numpy as jnp

    def body(x):
        return jax.lax.psum(x, "data")

    n = len(jax.devices())
    fn = jax.jit(sm(body, mesh=mesh, in_specs=P("data"),
                    out_specs=P("data")))
    x = jnp.arange(n * 2, dtype=jnp.float32).reshape(n, 2)
    out = np.asarray(fn(x))
    assert np.allclose(out, np.tile(x.sum(axis=0), (n, 1)))
