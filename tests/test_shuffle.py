"""Shuffle subsystem tests — serializer round-trips, the three manager
modes, transport SPI with a mock (reference strategy: unit-test distributed
logic at the SPI seam, RapidsShuffleClientSuite.scala:449), heartbeat
registry, and the ICI mesh data plane on the virtual 8-device mesh."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.convert import arrow_to_device, device_to_arrow
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.shuffle import (LocalTransport, ShuffleHeartbeatManager,
                                      ShuffleManager, concat_serialized,
                                      deserialize_batch, serialize_batch)
from spark_rapids_tpu.shuffle.transport import BlockId, PeerInfo


@pytest.fixture()
def sess():
    return srt.session()


def rich_table(n=200, seed=5):
    rng = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array([None if k % 11 == 0 else int(v) for k, v in
                       enumerate(rng.integers(-9999, 9999, n))],
                      type=pa.int64()),
        "f": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([None if k % 7 == 0 else f"str-{k}"
                       for k in range(n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "arr": pa.array([[k, k + 1] if k % 3 else [] for k in range(n)],
                        type=pa.list_(pa.int64())),
        "st": pa.array([{"a": k, "b": f"x{k}"} for k in range(n)],
                       type=pa.struct([("a", pa.int64()), ("b", pa.string())])),
    })


def test_serializer_roundtrip_rich_types():
    t = rich_table()
    b = arrow_to_device(t)
    frame = serialize_batch(b)
    rt = deserialize_batch(frame)
    back = device_to_arrow(rt)
    assert back.to_pylist() == t.to_pylist()


def test_serializer_packs_live_rows_only():
    t = rich_table(10)
    b = arrow_to_device(t, capacity=4096)  # huge padding
    frame_padded = serialize_batch(b)
    frame_tight = serialize_batch(arrow_to_device(t))
    # padding must not be shipped: both frames within a small delta
    assert abs(len(frame_padded) - len(frame_tight)) < 128


def test_concat_serialized():
    t = rich_table(50)
    b = arrow_to_device(t)
    out = concat_serialized([serialize_batch(b), serialize_batch(b)])
    assert out.num_rows_int == 100
    back = device_to_arrow(out)
    assert back.to_pylist() == t.to_pylist() + t.to_pylist()


@pytest.mark.parametrize("mode", ["SORT", "MULTITHREADED", "ICI"])
def test_manager_modes(tmp_path, mode):
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", mode)
    conf.set("spark.rapids.memory.spillDir", str(tmp_path))
    mgr = ShuffleManager(conf)
    t = rich_table(64)
    b = arrow_to_device(t)
    sid = mgr.new_shuffle_id()
    # 3 maps x 2 reduce partitions
    for m in range(3):
        mgr.write_map_output(sid, m, [b.sliced(0, 30), b.sliced(30, 34)])
    r0 = mgr.read_reduce_partition(sid, 3, 0)
    r1 = mgr.read_reduce_partition(sid, 3, 1)
    assert r0.num_rows_int == 90
    assert r1.num_rows_int == 102
    mgr.cleanup(sid)
    assert mgr.read_reduce_partition(sid, 3, 0) is None


def test_transport_spi_with_mock_fetch():
    """Unit-test the ICI fetch path with an injected transport failure +
    peer fallback — no cluster, no network (reference test strategy)."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    bmgr = ShuffleManager(conf, transport, "exec-B", hb)
    t = rich_table(20)
    batch = arrow_to_device(t)
    sid = 7
    # exec-B wrote the block; exec-A's local lookup misses, peer fetch hits
    bmgr.write_map_output(sid, 0, [batch])
    got = a.read_reduce_partition(sid, 1, 0)
    assert got is not None and got.num_rows_int == 20

    # injected failure: hook returns corrupted-frame marker for B's block
    calls = []

    def hook(peer, block):
        calls.append((peer.executor_id, block))
        return None  # fall through to the real store

    transport.fetch_hook = hook
    got2 = a.read_reduce_partition(sid, 1, 0)
    assert got2 is not None and got2.num_rows_int == 20
    assert any(p == "exec-B" for p, _ in calls)


def test_heartbeat_expiry():
    hb = ShuffleHeartbeatManager(heartbeat_timeout_s=0.0)
    hb.register("e1", "ep1")
    peers = hb.register("e2", "ep2")
    assert [p.executor_id for p in peers] == ["e1"]
    # timeout 0: the next heartbeat expires everyone else
    import time
    time.sleep(0.01)
    assert hb.heartbeat("e2") == []
    assert hb.executors() == ["e2"]


def test_exchange_through_manager_end_to_end(sess):
    """Multi-partition hash exchange through the real serializer path."""
    rng = np.random.default_rng(0)
    t = pa.table({"k": rng.integers(0, 20, 3000), "v": rng.random(3000)})
    df = sess.create_dataframe(t, num_partitions=5)
    from spark_rapids_tpu.sql import functions as F
    out = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"),
                               F.count("*").alias("c"))
           .collect().to_pandas().sort_values("k"))
    exp = t.to_pandas().groupby("k").agg(s=("v", "sum"), c=("v", "count"))
    assert np.allclose(out["s"].values, exp["s"].values)
    assert (out["c"].values == exp["c"].values).all()


def test_ici_mesh_data_plane():
    """Row exchange over the 8-device mesh via lax.all_to_all: every row
    lands on its hash-designated chip exactly once."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from spark_rapids_tpu.parallel.shuffle import build_ici_shuffle

    n_dev = 8
    rows_per = 64
    devices = np.array(jax.devices()[:n_dev])
    mesh = Mesh(devices, ("data",))
    exchange = build_ici_shuffle(mesh, "data", n_dev, rows_per)

    @partial(shard_map, mesh=mesh, in_specs=(P("data"), P("data")),
             out_specs=(P("data"), P("data")))
    def step(keys, vals):
        pids = (keys % n_dev).astype(jnp.int32)
        out, rvalid = exchange({"k": keys, "v": vals},
                               jnp.ones(keys.shape[0], bool), pids)
        # compact received rows: count + checksum per chip
        cnt = jnp.sum(rvalid).astype(jnp.int64)
        ksum = jnp.sum(jnp.where(rvalid, out["k"], 0))
        vsum = jnp.sum(jnp.where(rvalid, out["v"], 0.0))
        return cnt[None], jnp.stack([ksum.astype(jnp.float64), vsum])[None]

    rng = np.random.default_rng(3)
    keys = jnp.asarray(rng.integers(0, 1000, n_dev * rows_per))
    vals = jnp.asarray(rng.random(n_dev * rows_per))
    with mesh:
        cnts, sums = jax.jit(step)(keys, vals)
    cnts = np.asarray(cnts)
    assert cnts.sum() == n_dev * rows_per  # no rows lost or duplicated
    hk = np.asarray(keys)
    hv = np.asarray(vals)
    ks = np.asarray(sums)[:, 0]
    vs = np.asarray(sums)[:, 1]
    for d in range(n_dev):
        m = (hk % n_dev) == d
        assert ks[d] == hk[m].sum(), d
        assert np.isclose(vs[d], hv[m].sum()), d


def test_device_resident_local_tier(tmp_path):
    """Local SORT/MULTITHREADED blocks stay device-resident in the spill
    catalog (no serialize round trip) and serialize only when the tier is
    off (reference RapidsCachingWriter + ShuffleBufferCatalog)."""
    for resident, mode in ((True, "MULTITHREADED"), (False, "SORT")):
        conf = RapidsConf()
        conf.set("spark.rapids.shuffle.mode", mode)
        conf.set("spark.rapids.memory.spillDir", str(tmp_path))
        conf.set("spark.rapids.shuffle.localDeviceResident.enabled",
                 str(resident).lower())
        mgr = ShuffleManager(conf)
        t = rich_table(64)
        b = arrow_to_device(t)
        sid = mgr.new_shuffle_id()
        for m in range(2):
            mgr.write_map_output(sid, m, [b.sliced(0, 30), b.sliced(30, 34)])
        if resident:
            assert mgr._resident and not mgr._files
        else:
            assert mgr._files and not mgr._resident
        r0 = mgr.read_reduce_partition(sid, 2, 0)
        r1 = mgr.read_reduce_partition(sid, 2, 1)
        assert r0.num_rows_int == 60 and r1.num_rows_int == 68
        mgr.cleanup(sid)
        assert not mgr._resident and not mgr._files
        assert mgr.read_reduce_partition(sid, 2, 0) is None
