"""Shuffle subsystem tests — serializer round-trips, the three manager
modes, transport SPI with a mock (reference strategy: unit-test distributed
logic at the SPI seam, RapidsShuffleClientSuite.scala:449), heartbeat
registry, and the ICI mesh data plane on the virtual 8-device mesh."""

import socket
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.convert import arrow_to_device, device_to_arrow
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.shuffle import (FETCH_STATS, FrameCorrupt,
                                      LocalTransport, PeerBlacklist,
                                      ShuffleFetchFailed,
                                      ShuffleHeartbeatManager,
                                      ShuffleManager, concat_serialized,
                                      deserialize_batch, serialize_batch)
from spark_rapids_tpu.shuffle.transport import BlockId, PeerInfo


@pytest.fixture()
def sess():
    return srt.session()


def rich_table(n=200, seed=5):
    rng = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array([None if k % 11 == 0 else int(v) for k, v in
                       enumerate(rng.integers(-9999, 9999, n))],
                      type=pa.int64()),
        "f": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([None if k % 7 == 0 else f"str-{k}"
                       for k in range(n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "arr": pa.array([[k, k + 1] if k % 3 else [] for k in range(n)],
                        type=pa.list_(pa.int64())),
        "st": pa.array([{"a": k, "b": f"x{k}"} for k in range(n)],
                       type=pa.struct([("a", pa.int64()), ("b", pa.string())])),
    })


def test_serializer_roundtrip_rich_types():
    t = rich_table()
    b = arrow_to_device(t)
    frame = serialize_batch(b)
    rt = deserialize_batch(frame)
    back = device_to_arrow(rt)
    assert back.to_pylist() == t.to_pylist()


def test_serializer_packs_live_rows_only():
    from spark_rapids_tpu.config import RapidsConf
    t = rich_table(10)
    b = arrow_to_device(t, capacity=4096)  # huge padding
    # dictionary refs off: the second frame would otherwise replace its
    # (identical) dictionary with a registry ref, shrinking it for a
    # reason unrelated to the padding contract under test
    conf = RapidsConf(
        {"spark.rapids.tpu.sql.encoded.shuffle.dictRefs.enabled": False})
    frame_padded = serialize_batch(b, conf)
    frame_tight = serialize_batch(arrow_to_device(t), conf)
    # padding must not be shipped: both frames within a small delta
    assert abs(len(frame_padded) - len(frame_tight)) < 128


def test_concat_serialized():
    t = rich_table(50)
    b = arrow_to_device(t)
    out = concat_serialized([serialize_batch(b), serialize_batch(b)])
    assert out.num_rows_int == 100
    back = device_to_arrow(out)
    assert back.to_pylist() == t.to_pylist() + t.to_pylist()


@pytest.mark.parametrize("mode", ["SORT", "MULTITHREADED", "ICI"])
def test_manager_modes(tmp_path, mode):
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", mode)
    conf.set("spark.rapids.memory.spillDir", str(tmp_path))
    mgr = ShuffleManager(conf)
    t = rich_table(64)
    b = arrow_to_device(t)
    sid = mgr.new_shuffle_id()
    # 3 maps x 2 reduce partitions
    for m in range(3):
        mgr.write_map_output(sid, m, [b.sliced(0, 30), b.sliced(30, 34)])
    r0 = mgr.read_reduce_partition(sid, 3, 0)
    r1 = mgr.read_reduce_partition(sid, 3, 1)
    assert r0.num_rows_int == 90
    assert r1.num_rows_int == 102
    mgr.cleanup(sid)
    assert mgr.read_reduce_partition(sid, 3, 0) is None


def test_transport_spi_with_mock_fetch():
    """Unit-test the ICI fetch path with an injected transport failure +
    peer fallback — no cluster, no network (reference test strategy)."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    bmgr = ShuffleManager(conf, transport, "exec-B", hb)
    t = rich_table(20)
    batch = arrow_to_device(t)
    sid = 7
    # exec-B wrote the block; exec-A's local lookup misses, peer fetch hits
    bmgr.write_map_output(sid, 0, [batch])
    got = a.read_reduce_partition(sid, 1, 0)
    assert got is not None and got.num_rows_int == 20

    # injected failure: hook returns corrupted-frame marker for B's block
    calls = []

    def hook(peer, block):
        calls.append((peer.executor_id, block))
        return None  # fall through to the real store

    transport.fetch_hook = hook
    got2 = a.read_reduce_partition(sid, 1, 0)
    assert got2 is not None and got2.num_rows_int == 20
    assert any(p == "exec-B" for p, _ in calls)


def test_heartbeat_expiry():
    hb = ShuffleHeartbeatManager(heartbeat_timeout_s=0.0)
    hb.register("e1", "ep1")
    peers = hb.register("e2", "ep2")
    assert [p.executor_id for p in peers] == ["e1"]
    # timeout 0: the next heartbeat expires everyone else
    import time
    time.sleep(0.01)
    assert hb.heartbeat("e2") == []
    assert hb.executors() == ["e2"]


def test_exchange_through_manager_end_to_end(sess):
    """Multi-partition hash exchange through the real serializer path."""
    rng = np.random.default_rng(0)
    t = pa.table({"k": rng.integers(0, 20, 3000), "v": rng.random(3000)})
    df = sess.create_dataframe(t, num_partitions=5)
    from spark_rapids_tpu.sql import functions as F
    out = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"),
                               F.count("*").alias("c"))
           .collect().to_pandas().sort_values("k"))
    exp = t.to_pandas().groupby("k").agg(s=("v", "sum"), c=("v", "count"))
    assert np.allclose(out["s"].values, exp["s"].values)
    assert (out["c"].values == exp["c"].values).all()


def test_ici_mesh_data_plane():
    """Row exchange over the 8-device mesh via lax.all_to_all: every row
    lands on its hash-designated chip exactly once."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        pytest.skip("jax.shard_map unavailable in this environment")
    from spark_rapids_tpu.parallel.shuffle import build_ici_shuffle

    n_dev = 8
    rows_per = 64
    devices = np.array(jax.devices()[:n_dev])
    mesh = Mesh(devices, ("data",))
    exchange = build_ici_shuffle(mesh, "data", n_dev, rows_per)

    @partial(shard_map, mesh=mesh, in_specs=(P("data"), P("data")),
             out_specs=(P("data"), P("data")))
    def step(keys, vals):
        pids = (keys % n_dev).astype(jnp.int32)
        out, rvalid = exchange({"k": keys, "v": vals},
                               jnp.ones(keys.shape[0], bool), pids)
        # compact received rows: count + checksum per chip
        cnt = jnp.sum(rvalid).astype(jnp.int64)
        ksum = jnp.sum(jnp.where(rvalid, out["k"], 0))
        vsum = jnp.sum(jnp.where(rvalid, out["v"], 0.0))
        return cnt[None], jnp.stack([ksum.astype(jnp.float64), vsum])[None]

    rng = np.random.default_rng(3)
    keys = jnp.asarray(rng.integers(0, 1000, n_dev * rows_per))
    vals = jnp.asarray(rng.random(n_dev * rows_per))
    with mesh:
        cnts, sums = jax.jit(step)(keys, vals)
    cnts = np.asarray(cnts)
    assert cnts.sum() == n_dev * rows_per  # no rows lost or duplicated
    hk = np.asarray(keys)
    hv = np.asarray(vals)
    ks = np.asarray(sums)[:, 0]
    vs = np.asarray(sums)[:, 1]
    for d in range(n_dev):
        m = (hk % n_dev) == d
        assert ks[d] == hk[m].sum(), d
        assert np.isclose(vs[d], hv[m].sum()), d


def test_device_resident_local_tier(tmp_path):
    """Local SORT/MULTITHREADED blocks stay device-resident in the spill
    catalog (no serialize round trip) and serialize only when the tier is
    off (reference RapidsCachingWriter + ShuffleBufferCatalog)."""
    for resident, mode in ((True, "MULTITHREADED"), (False, "SORT")):
        conf = RapidsConf()
        conf.set("spark.rapids.shuffle.mode", mode)
        conf.set("spark.rapids.memory.spillDir", str(tmp_path))
        conf.set("spark.rapids.shuffle.localDeviceResident.enabled",
                 str(resident).lower())
        mgr = ShuffleManager(conf)
        t = rich_table(64)
        b = arrow_to_device(t)
        sid = mgr.new_shuffle_id()
        for m in range(2):
            mgr.write_map_output(sid, m, [b.sliced(0, 30), b.sliced(30, 34)])
        if resident:
            assert mgr._resident and not mgr._files
        else:
            assert mgr._files and not mgr._resident
        r0 = mgr.read_reduce_partition(sid, 2, 0)
        r1 = mgr.read_reduce_partition(sid, 2, 1)
        assert r0.num_rows_int == 60 and r1.num_rows_int == 68
        mgr.cleanup(sid)
        assert not mgr._resident and not mgr._files
        assert mgr.read_reduce_partition(sid, 2, 0) is None


# ---------------------------------------------------------------------------
# resilient fetch protocol: retry/backoff/deadline, blacklist, recompute
# ---------------------------------------------------------------------------

def _ici_pair(fetch_conf=None):
    """exec-A reading blocks exec-B published over a shared mock
    transport — the SPI seam every protocol test drives."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    for k, v in (fetch_conf or {}).items():
        conf.set(k, v)
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    b = ShuffleManager(conf, transport, "exec-B", hb)
    return a, b, transport


def test_fetch_retry_backoff_ordering(monkeypatch):
    """Transient fetch failures retry with exponentially increasing
    backoff (plus jitter) and then succeed; retries are counted."""
    a, b, transport = _ici_pair({
        "spark.rapids.tpu.shuffle.fetch.maxRetries": 6,
        "spark.rapids.tpu.shuffle.fetch.backoffMs": 20,
    })
    batch = arrow_to_device(rich_table(16))
    b.write_map_output(9, 0, [batch])

    fails = [3]

    def hook(peer, block):
        if fails[0] > 0:
            fails[0] -= 1
            raise ShuffleFetchFailed("transient (test hook)")
        return None  # fall through to the real store

    transport.fetch_hook = hook
    delays = []
    real_sleep = time.sleep
    monkeypatch.setattr(time, "sleep", lambda s: delays.append(s))
    retries0 = FETCH_STATS["retries"]
    got = a.read_reduce_partition(9, 1, 0)
    monkeypatch.setattr(time, "sleep", real_sleep)
    assert got is not None and got.num_rows_int == 16
    assert FETCH_STATS["retries"] - retries0 == 3
    assert len(delays) == 3
    # exponential ordering: each delay at least the base, monotonically
    # increasing, jitter bounded at +25%
    assert delays[0] >= 0.02 and delays[0] <= 0.02 * 1.26
    assert delays[0] < delays[1] < delays[2]
    assert delays[2] <= 0.08 * 1.26


def test_fetch_deadline_expiry():
    """The per-reduce deadline bounds the retry loop even when
    maxRetries would allow many more attempts."""
    a, b, transport = _ici_pair({
        "spark.rapids.tpu.shuffle.fetch.maxRetries": 1000,
        "spark.rapids.tpu.shuffle.fetch.backoffMs": 30,
        "spark.rapids.tpu.shuffle.fetch.deadlineMs": 120,
    })
    batch = arrow_to_device(rich_table(16))
    b.write_map_output(3, 0, [batch])

    def hook(peer, block):
        raise ShuffleFetchFailed("always down (test hook)")

    transport.fetch_hook = hook
    t0 = time.monotonic()
    with pytest.raises(ShuffleFetchFailed):
        a.read_reduce_partition(3, 1, 0)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, "deadline must stop a 1000-retry budget early"


def test_timeout_surfaces_as_shuffle_fetch_failed():
    """Regression (satellite): a socket.timeout (OSError subclass) from
    the transport must surface as ShuffleFetchFailed — never a bare
    network exception, never a silent None masquerading as an empty
    partition."""
    a, b, transport = _ici_pair({
        "spark.rapids.tpu.shuffle.fetch.maxRetries": 0,
        "spark.rapids.tpu.shuffle.fetch.backoffMs": 1,
    })
    batch = arrow_to_device(rich_table(8))
    b.write_map_output(4, 0, [batch])

    def hook(peer, block):
        raise socket.timeout("recv timed out (test hook)")

    transport.fetch_hook = hook
    with pytest.raises(ShuffleFetchFailed) as ei:
        a.read_reduce_partition(4, 1, 0)
    assert isinstance(ei.value.__cause__, socket.timeout)


def test_peer_blacklist_unit():
    bl = PeerBlacklist(threshold=2, ttl_s=0.05)
    assert bl.record_failure("p1") is False
    assert bl.record_failure("p1") is True      # newly blacklisted
    assert bl.record_failure("p1") is False     # already benched
    assert bl.is_blacklisted("p1")
    peers = [PeerInfo("p1", "e1"), PeerInfo("p2", "e2")]
    assert [p.executor_id for p in bl.order(peers)] == ["p2", "p1"]
    time.sleep(0.06)
    assert bl.reinstate_expired() == ["p1"]     # heartbeat-driven
    assert not bl.is_blacklisted("p1")
    assert [p.executor_id for p in bl.order(peers)] == ["p1", "p2"]
    # a success clears strikes immediately
    bl.record_failure("p2")
    bl.record_success("p2")
    assert bl.record_failure("p2") is False


def test_peer_blacklist_integration():
    """A repeatedly-failing peer gets benched (counted) and drops to
    last-resort ordering; a healthy peer still serves the block."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "ICI")
    conf.set("spark.rapids.tpu.shuffle.fetch.maxRetries", 0)
    conf.set("spark.rapids.tpu.shuffle.fetch.blacklistAfter", 2)
    hb = ShuffleHeartbeatManager()
    transport = LocalTransport()
    a = ShuffleManager(conf, transport, "exec-A", hb)
    bad = ShuffleManager(conf, transport, "exec-BAD", hb)
    good = ShuffleManager(conf, transport, "exec-GOOD", hb)
    batch = arrow_to_device(rich_table(12))
    good.write_map_output(5, 0, [batch])

    calls = []

    def hook(peer, block):
        calls.append(peer.executor_id)
        if peer.executor_id == "exec-BAD":
            raise ShuffleFetchFailed("peer dead (test hook)")
        return None

    transport.fetch_hook = hook
    bl0 = FETCH_STATS["blacklisted"]
    for _ in range(3):
        got = a.read_reduce_partition(5, 1, 0)
        assert got is not None and got.num_rows_int == 12
    assert FETCH_STATS["blacklisted"] - bl0 == 1
    assert a._blacklist.is_blacklisted("exec-BAD")
    # benched peer is ordered last on the next read: the healthy peer is
    # tried (and answers) before exec-BAD is ever contacted
    calls.clear()
    a.read_reduce_partition(5, 1, 0)
    peer_calls = [c for c in calls if c != "exec-A"]
    assert peer_calls and peer_calls[0] == "exec-GOOD"


def test_lost_block_recompute_bit_parity(tmp_path):
    """Destroying a committed block's backing file and re-reading through
    the registered lineage callback reproduces the partition
    bit-identically (the FetchFailed->stage-retry contract at batch
    granularity)."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "SORT")
    conf.set("spark.rapids.memory.spillDir", str(tmp_path))
    conf.set("spark.rapids.shuffle.localDeviceResident.enabled", "false")
    mgr = ShuffleManager(conf)
    t = rich_table(64)
    b = arrow_to_device(t)
    sid = mgr.new_shuffle_id()
    pieces = {0: [b.sliced(0, 30), b.sliced(30, 34)],
              1: [b.sliced(34, 20), b.sliced(54, 10)]}
    for m, ps in pieces.items():
        mgr.write_map_output(sid, m, ps)
    baseline = device_to_arrow(
        mgr.read_reduce_partition(sid, 2, 0)).to_pylist()

    mgr.register_recompute(
        sid, lambda map_id: mgr.write_map_output(sid, map_id,
                                                 pieces[map_id]))
    import os
    victim = BlockId(sid, 1, 0)
    os.unlink(mgr._files[victim])
    rec0 = FETCH_STATS["recomputed"]
    again = device_to_arrow(
        mgr.read_reduce_partition(sid, 2, 0)).to_pylist()
    assert FETCH_STATS["recomputed"] - rec0 == 1
    assert again == baseline


def test_no_recompute_without_lineage_raises(tmp_path):
    """Without a registered callback, a lost committed block fails the
    read loudly — it must not read back as an empty partition."""
    conf = RapidsConf()
    conf.set("spark.rapids.shuffle.mode", "SORT")
    conf.set("spark.rapids.memory.spillDir", str(tmp_path))
    conf.set("spark.rapids.shuffle.localDeviceResident.enabled", "false")
    conf.set("spark.rapids.tpu.shuffle.fetch.backoffMs", 1)
    mgr = ShuffleManager(conf)
    b = arrow_to_device(rich_table(16))
    sid = mgr.new_shuffle_id()
    mgr.write_map_output(sid, 0, [b])
    import os
    os.unlink(mgr._files[BlockId(sid, 0, 0)])
    with pytest.raises(ShuffleFetchFailed):
        mgr.read_reduce_partition(sid, 1, 0)


def test_torn_frame_stream_raises():
    from spark_rapids_tpu.shuffle.manager import pack_frames, split_frames
    blob = pack_frames([b"abcdef", b"0123"])
    assert split_frames(blob) == [b"abcdef", b"0123"]
    with pytest.raises(FrameCorrupt):
        split_frames(blob[:-1])          # torn final frame
    with pytest.raises(FrameCorrupt):
        split_frames(blob + b"\x01")     # torn length prefix
