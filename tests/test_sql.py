"""SQL front-end tests — session.sql / selectExpr / expr / string filters.

The reference accelerates SQL text transparently (every Spark query is SQL
compiled by Catalyst before the plugin runs; SURVEY §1).  These tests drive
the same engine through SQL strings and check against pandas oracles or the
equivalent DataFrame-API query.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.sqlparser import SqlParseError


@pytest.fixture()
def spark(session):
    return session


@pytest.fixture()
def t(spark):
    df = spark.createDataFrame(
        [(1, "a", 10.0), (2, "b", 20.0), (1, "c", 30.0),
         (3, None, 40.0), (2, "b", 5.5), (1, "a", None)],
        "k int, s string, v double")
    df.createOrReplaceTempView("t")
    return df


def rows(df):
    return df.collect().to_pylist()


# --- expression strings ----------------------------------------------------

def test_expr_arithmetic_precedence(spark, t):
    out = rows(t.select(F.expr("k + 2 * 3").alias("x")))
    assert [r["x"] for r in out] == [7, 8, 7, 9, 8, 7]


def test_expr_string_functions(spark, t):
    out = rows(t.select(F.expr("upper(concat(s, '!'))").alias("x")))
    assert [r["x"] for r in out] == ["A!", "B!", "C!", None, "B!", "A!"]


def test_expr_concat_pipes(spark, t):
    out = rows(t.select(F.expr("s || '_' || s").alias("x")))
    assert out[0]["x"] == "a_a"


def test_filter_string_predicates(spark, t):
    got = rows(t.filter("v > 10 AND s IS NOT NULL"))
    assert [(r["k"], r["s"]) for r in got] == [(2, "b"), (1, "c")]


def test_filter_string_in_between_like(spark, t):
    assert len(rows(t.filter("k IN (1, 3)"))) == 4
    assert len(rows(t.filter("v BETWEEN 10 AND 30"))) == 3
    assert len(rows(t.filter("s LIKE 'a%'"))) == 2
    assert len(rows(t.filter("s NOT LIKE 'a%'"))) == 3  # null drops
    assert len(rows(t.filter("s RLIKE '^[ab]$'"))) == 4


def test_selectExpr(spark, t):
    out = rows(t.selectExpr("k", "v * 2 AS w", "upper(s) u"))
    assert set(out[0]) == {"k", "w", "u"}
    assert out[1]["w"] == 40.0 and out[1]["u"] == "B"


def test_selectExpr_star(spark, t):
    out = t.selectExpr("*", "k + 1 AS k2")
    assert out.columns == ["k", "s", "v", "k2"]


def test_number_literal_types(spark, t):
    tab = t.selectExpr("1 AS a", "1.5 AS b", "1e2 AS c", "2L AS d",
                       "3d AS e").collect()
    import pyarrow as pa
    assert tab.schema.field("a").type == pa.int32()
    assert tab.schema.field("b").type == pa.float64()
    assert tab.schema.field("c").type == pa.float64()
    assert tab.schema.field("d").type == pa.int64()
    assert tab.schema.field("e").type == pa.float64()


def test_case_when(spark, t):
    out = rows(t.selectExpr(
        "CASE WHEN v > 15 THEN 'hi' WHEN v > 8 THEN 'mid' ELSE 'lo' END c"))
    assert [r["c"] for r in out] == ["mid", "hi", "hi", "hi", "lo", "lo"]
    # simple-subject form
    out = rows(t.selectExpr("CASE k WHEN 1 THEN 'one' ELSE 'many' END c"))
    assert [r["c"] for r in out] == ["one", "many", "one", "many", "many",
                                     "one"]


def test_cast_and_types(spark, t):
    out = rows(t.selectExpr("CAST(v AS int) i", "CAST(k AS string) s2",
                            "CAST(v AS decimal(5,1)) d"))
    assert out[0]["i"] == 10
    assert out[0]["s2"] == "1"


def test_is_null_not(spark, t):
    assert len(rows(t.filter("s IS NULL"))) == 1
    assert len(rows(t.filter("v IS NOT NULL AND NOT (k = 1)"))) == 3


# --- session.sql -----------------------------------------------------------

def test_sql_basic_projection(spark, t):
    got = rows(spark.sql("SELECT k, v FROM t WHERE v >= 10 ORDER BY v"))
    assert got == [{"k": 1, "v": 10.0}, {"k": 2, "v": 20.0},
                   {"k": 1, "v": 30.0}, {"k": 3, "v": 40.0}]


def test_sql_select_star(spark, t):
    assert spark.sql("SELECT * FROM t").columns == ["k", "s", "v"]


def test_sql_no_from(spark):
    got = rows(spark.sql("SELECT 1 + 1 AS two, upper('x') AS u"))
    assert got == [{"two": 2, "u": "X"}]


def test_sql_group_by(spark, t):
    got = rows(spark.sql(
        "SELECT k, sum(v) AS total, count(*) AS n, count(v) AS nv "
        "FROM t GROUP BY k ORDER BY k"))
    assert got == [
        {"k": 1, "total": 40.0, "n": 3, "nv": 2},
        {"k": 2, "total": 25.5, "n": 2, "nv": 2},
        {"k": 3, "total": 40.0, "n": 1, "nv": 1}]


def test_sql_group_by_ordinal_and_alias(spark, t):
    a = rows(spark.sql("SELECT k AS kk, avg(v) a FROM t GROUP BY 1 ORDER BY 1"))
    b = rows(spark.sql("SELECT k AS kk, avg(v) a FROM t GROUP BY kk ORDER BY kk"))
    assert a == b
    assert a[0]["kk"] == 1 and a[0]["a"] == 20.0


def test_sql_group_by_expression(spark, t):
    got = rows(spark.sql(
        "SELECT k % 2 AS odd, count(*) n FROM t GROUP BY k % 2 ORDER BY odd"))
    assert got == [{"odd": 0, "n": 2}, {"odd": 1, "n": 4}]


def test_sql_select_list_order_differs_from_groups(spark, t):
    # aggregate first in the select list — plan must not force key-first
    got = rows(spark.sql(
        "SELECT sum(v) AS total, k FROM t GROUP BY k ORDER BY k"))
    assert got[0] == {"total": 40.0, "k": 1}


def test_sql_having(spark, t):
    got = rows(spark.sql(
        "SELECT k, sum(v) s FROM t GROUP BY k HAVING sum(v) > 30 ORDER BY k"))
    assert [r["k"] for r in got] == [1, 3]
    # HAVING over an aggregate that is NOT in the select list
    got = rows(spark.sql(
        "SELECT k FROM t GROUP BY k HAVING count(*) >= 2 ORDER BY k"))
    assert [r["k"] for r in got] == [1, 2]


def test_sql_global_aggregate(spark, t):
    got = rows(spark.sql("SELECT sum(v) s, max(k) m FROM t"))
    assert got == [{"s": 105.5, "m": 3}]


def test_sql_order_by_hidden_column(spark, t):
    # ORDER BY a column that is not in the select list
    got = rows(spark.sql("SELECT s FROM t WHERE v IS NOT NULL ORDER BY v DESC"))
    assert [r["s"] for r in got] == [None, "c", "b", "a", "b"]


def test_sql_order_by_agg_not_in_select(spark, t):
    got = rows(spark.sql(
        "SELECT k FROM t GROUP BY k ORDER BY sum(v) DESC, k"))
    assert [r["k"] for r in got] == [1, 3, 2]


def test_sql_distinct(spark, t):
    got = rows(spark.sql("SELECT DISTINCT k FROM t ORDER BY k"))
    assert [r["k"] for r in got] == [1, 2, 3]


def test_sql_count_distinct(spark, t):
    got = rows(spark.sql("SELECT count(DISTINCT k) ck FROM t"))
    assert got[0]["ck"] == 3
    got = rows(spark.sql("SELECT sum(DISTINCT v) sv FROM t"))
    assert got[0]["sv"] == 105.5


def test_sql_limit_offset(spark, t):
    got = rows(spark.sql("SELECT v FROM t WHERE v IS NOT NULL "
                         "ORDER BY v LIMIT 2 OFFSET 1"))
    assert [r["v"] for r in got] == [10.0, 20.0]


def test_sql_join(spark, t):
    d = spark.createDataFrame([(1, "x"), (2, "y"), (9, "z")],
                              "k int, name string")
    d.createOrReplaceTempView("d")
    got = rows(spark.sql(
        "SELECT t.k, d.name, t.v FROM t JOIN d ON t.k = d.k "
        "WHERE t.v IS NOT NULL ORDER BY t.v"))
    assert [(r["k"], r["name"]) for r in got] == [
        (2, "y"), (1, "x"), (2, "y"), (1, "x")]
    # left join keeps unmatched
    got = rows(spark.sql(
        "SELECT t.k, d.name FROM t LEFT JOIN d ON t.k = d.k ORDER BY t.k"))
    assert {(r["k"], r["name"]) for r in got} == {
        (1, "x"), (2, "y"), (3, None)}


def test_sql_join_using(spark, t):
    d = spark.createDataFrame([(1, "x"), (2, "y")], "k int, name string")
    d.createOrReplaceTempView("d2")
    df = spark.sql("SELECT * FROM t JOIN d2 USING (k)")
    assert df.columns == ["k", "s", "v", "name"]


def test_sql_join_aliases(spark, t):
    got = rows(spark.sql(
        "SELECT a.k, b.v AS bv FROM t a JOIN t b ON a.k = b.k "
        "WHERE a.v = 10.0 AND b.v = 30.0"))
    assert got == [{"k": 1, "bv": 30.0}]


def test_sql_subquery(spark, t):
    got = rows(spark.sql(
        "SELECT k, total FROM (SELECT k, sum(v) AS total FROM t GROUP BY k) "
        "WHERE total > 30 ORDER BY k"))
    assert [r["k"] for r in got] == [1, 3]


def test_sql_cte(spark, t):
    got = rows(spark.sql(
        "WITH agg AS (SELECT k, sum(v) AS total FROM t GROUP BY k), "
        "big AS (SELECT * FROM agg WHERE total > 30) "
        "SELECT k FROM big ORDER BY k"))
    assert [r["k"] for r in got] == [1, 3]


def test_sql_union(spark, t):
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k = 1 UNION SELECT k FROM t WHERE k <= 2 "
        "ORDER BY k"))
    assert [r["k"] for r in got] == [1, 2]
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k = 3 UNION ALL SELECT k FROM t WHERE k = 3"))
    assert [r["k"] for r in got] == [3, 3]


def test_sql_setop_trailing_clauses_bind_to_result(spark, t):
    # LIMIT/ORDER BY after a UNION applies to the whole result, not the
    # last branch
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k = 1 UNION ALL SELECT k FROM t LIMIT 2"))
    assert len(got) == 2
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k = 3 UNION ALL SELECT k FROM t WHERE k = 2 "
        "ORDER BY k DESC"))
    assert [r["k"] for r in got] == [3, 2, 2]


def test_sql_intersect_binds_tighter_than_union(spark, t):
    # a UNION (b INTERSECT c), not (a UNION b) INTERSECT c
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k = 3 "
        "UNION SELECT k + 10 AS k FROM t "
        "INTERSECT SELECT k + 10 AS k FROM t WHERE k = 1 ORDER BY k"))
    assert [r["k"] for r in got] == [3, 11]


def test_sql_operator_precedence(spark):
    got = rows(spark.sql(
        "SELECT 2 | 1 + 1 AS a, 2 ^ 3 & 1 AS b, 1 << 2 + 1 AS c, "
        "'a' || 1 + 1 AS d, -2L AS e"))
    # Spark: | loosest, then ^, then &, then shifts, then ||, then +/-
    assert got == [{"a": 2, "b": 3, "c": 8, "d": "a2", "e": -2}]
    tab = spark.sql("SELECT -2L AS e").collect()
    import pyarrow as pa
    assert tab.schema.field("e").type == pa.int64()


def test_sql_count_distinct_star_rejected(spark, t):
    with pytest.raises(SqlParseError):
        spark.sql("SELECT count(DISTINCT *) FROM t")


def test_sql_bad_ordinals_are_parse_errors(spark, t):
    for bad in ("SELECT k FROM t GROUP BY 1e1",
                "SELECT k FROM t ORDER BY k LIMIT 1e1"):
        with pytest.raises(SqlParseError):
            spark.sql(bad)


def test_sql_union_all_distinct_rejected(spark, t):
    with pytest.raises(SqlParseError):
        spark.sql("SELECT k FROM t UNION ALL DISTINCT SELECT k FROM t")


def test_sql_window_in_where_rejected(spark, t):
    with pytest.raises(SqlParseError):
        spark.sql("SELECT k FROM t "
                  "WHERE sum(v) OVER (PARTITION BY k) > 20")


def test_sql_non_sql_helpers_not_functions(spark, t):
    for bad in ("lit(1)", "col('k')", "expr_fn(k)"):
        with pytest.raises(SqlParseError, match="unknown SQL function"):
            spark.sql(f"SELECT {bad} FROM t")


def test_sql_unknown_column_is_parse_error(spark, t):
    with pytest.raises(SqlParseError):
        spark.sql("SELECT nope FROM t")
    with pytest.raises(SqlParseError):
        spark.sql("SELECT k FROM t ORDER BY nope")


def test_sql_except_intersect(spark, t):
    got = rows(spark.sql(
        "SELECT k FROM t EXCEPT SELECT k FROM t WHERE k = 1 ORDER BY k"))
    assert [r["k"] for r in got] == [2, 3]
    got = rows(spark.sql(
        "SELECT k FROM t WHERE k <= 2 INTERSECT SELECT k FROM t WHERE k >= 2"))
    assert [r["k"] for r in got] == [2]


def test_sql_window_function(spark, t):
    got = rows(spark.sql(
        "SELECT k, v, row_number() OVER (PARTITION BY k ORDER BY v) rn "
        "FROM t WHERE v IS NOT NULL ORDER BY k, v"))
    assert [(r["k"], r["rn"]) for r in got] == [
        (1, 1), (1, 2), (2, 1), (2, 2), (3, 1)]


def test_sql_window_running_sum(spark, t):
    got = rows(spark.sql(
        "SELECT k, v, sum(v) OVER (PARTITION BY k ORDER BY v "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) rs "
        "FROM t WHERE v IS NOT NULL ORDER BY k, v"))
    assert [r["rs"] for r in got] == [10.0, 40.0, 5.5, 25.5, 40.0]


def test_sql_oracle_tpch_q1_shape(spark):
    rng = np.random.default_rng(0)
    n = 5000
    pdf = pd.DataFrame({
        "rf": rng.choice(["A", "N", "R"], n),
        "ls": rng.choice(["O", "F"], n),
        "qty": rng.integers(1, 51, n).astype("float64"),
        "price": rng.random(n) * 1000,
        "disc": rng.random(n) * 0.1,
    })
    spark.createDataFrame(pdf).createOrReplaceTempView("lineitem")
    got = spark.sql(
        "SELECT rf, ls, sum(qty) AS sum_qty, "
        "sum(price * (1 - disc)) AS sum_disc_price, "
        "avg(price) AS avg_price, count(*) AS n "
        "FROM lineitem WHERE qty < 24 "
        "GROUP BY rf, ls ORDER BY rf, ls").collect().to_pandas()
    exp = (pdf[pdf.qty < 24]
           .assign(sum_disc_price=lambda d: d.price * (1 - d.disc))
           .groupby(["rf", "ls"], as_index=False)
           .agg(sum_qty=("qty", "sum"), sum_disc_price=("sum_disc_price", "sum"),
                avg_price=("price", "mean"), n=("rf", "size"))
           .sort_values(["rf", "ls"]).reset_index(drop=True))
    pd.testing.assert_frame_equal(
        got, exp[got.columns.tolist()], check_dtype=False, atol=1e-6)


def test_sql_errors(spark, t):
    with pytest.raises(SqlParseError):
        spark.sql("SELECT nope(")
    with pytest.raises(SqlParseError):
        spark.sql("SELECT v FROM t GROUP BY k")   # v not grouped
    with pytest.raises(SqlParseError):
        spark.sql("SELECT * FROM t WHERE sum(v) > 1")  # agg in WHERE
    with pytest.raises(SqlParseError):
        spark.sql("SELECT unknown_fn(v) FROM t")
    with pytest.raises(SqlParseError):
        spark.sql("SELECT t2.v FROM t")           # unknown alias
    with pytest.raises(ValueError):
        spark.sql("SELECT * FROM no_such_view")


def test_catalog(spark, t):
    assert spark.catalog.tableExists("t")
    assert "t" in spark.catalog.listTables()
    assert rows(spark.table("t")) == rows(t)
    spark.sql("SELECT 1").collect()               # catalog untouched
    assert spark.catalog.dropTempView("t")
    assert not spark.catalog.tableExists("t")


def test_interval_arithmetic(spark):
    """INTERVAL 'n' unit in date/timestamp +/- arithmetic (TPC-H spec
    cutoffs: DATE '1998-12-01' - INTERVAL '90' DAY)."""
    import datetime
    import pyarrow as pa
    rng = np.random.default_rng(3)
    n = 2000
    base = np.datetime64("1996-01-01")
    d = (base + rng.integers(0, 1000, n).astype("timedelta64[D]")
         ).astype("datetime64[D]")
    t = pa.table({"d": pa.array(d)})
    pdf = t.to_pandas()
    spark.create_dataframe(t).createOrReplaceTempView("t_iv")
    got = spark.sql(
        "SELECT count(*) AS c FROM t_iv WHERE d <= "
        "CAST('1998-12-01' AS date) - INTERVAL '90' DAY"
    ).collect().to_pylist()[0]["c"]
    cutoff = datetime.date(1998, 12, 1) - datetime.timedelta(days=90)
    assert got == int((pdf.d <= cutoff).sum())
    # month arithmetic clamps to month end; interval commutes over +
    got = spark.sql("SELECT (INTERVAL '1' YEAR + CAST('2000-02-29' AS "
                   "date)) AS d2").collect().to_pylist()[0]["d2"]
    assert got == datetime.date(2001, 2, 28)
    got = spark.sql("SELECT CAST('2000-01-01' AS date) + "
                   "INTERVAL '1' MONTH 10 DAYS AS d2"
                   ).collect().to_pylist()[0]["d2"]
    assert got == datetime.date(2000, 2, 11)
    with pytest.raises(ValueError):
        spark.sql("SELECT INTERVAL '1' DAY + INTERVAL '2' DAY AS x"
                  ).collect()
    # operand-type dispatch: timestamp keeps sub-day precision, a date
    # with a sub-day interval promotes to timestamp, month arithmetic is
    # calendar-aware, and subtraction may CHAIN after an interval
    ts = pa.table({"ts": pa.array(
        [datetime.datetime(2020, 1, 31, 10)], type=pa.timestamp("us")),
        "d2": pa.array([datetime.date(2020, 1, 31)], type=pa.date32())})
    spark.create_dataframe(ts).createOrReplaceTempView("t_iv2")
    r = spark.sql(
        "SELECT ts + INTERVAL '1' MONTH AS b, d2 + INTERVAL '2' HOUR AS c,"
        " ts + INTERVAL '1' DAY - INTERVAL '1' DAY AS f FROM t_iv2"
    ).collect().to_pylist()[0]

    def naive(x):
        return x.replace(tzinfo=None) if getattr(x, "tzinfo", None) else x
    assert naive(r["b"]) == datetime.datetime(2020, 2, 29, 10)
    assert naive(r["c"]) == datetime.datetime(2020, 1, 31, 2)
    assert naive(r["f"]) == datetime.datetime(2020, 1, 31, 10)


def test_string_literal_backslash_escapes(spark):
    """Spark default (escapedStringLiterals=false): '\\\\d' is the 2-char
    regex escape, '\\n' a newline, '' a quote, \\% keeps its backslash."""
    tt = pa.table({"s": ["alpha1", "x", "a\nb"]})
    spark.create_dataframe(tt).createOrReplaceTempView("esc_t")
    out = spark.sql(
        r"SELECT s RLIKE '[a-z]+\\d+' AS m, 'a\nb' = s AS nl, "
        r"length('it''s') AS q, 'p\\%q' AS pct FROM esc_t"
    ).collect().to_pylist()
    assert [r["m"] for r in out] == [True, False, False]
    assert [r["nl"] for r in out] == [False, False, True]
    assert out[0]["q"] == 4
    assert out[0]["pct"] == "p\\%q"


def test_show_tables_and_describe(spark, t):
    out = spark.sql("SHOW TABLES").collect().to_pylist()
    assert any(r["tableName"] == "t" and r["isTemporary"] for r in out)
    d = spark.sql("DESCRIBE TABLE t").collect().to_pylist()
    assert [r["col_name"] for r in d] == ["k", "s", "v"]
    assert [r["data_type"] for r in d] == ["int", "string", "double"]
    assert spark.sql("DESC t").collect().num_rows == 3
    with pytest.raises(ValueError, match="not found"):
        spark.sql("DESCRIBE no_such_view").collect()


def test_create_and_drop_temp_view(spark, t):
    spark.sql("CREATE TEMP VIEW tv_agg AS "
              "SELECT k, sum(v) AS s FROM t GROUP BY k")
    out = spark.sql("SELECT * FROM tv_agg ORDER BY k").collect().to_pylist()
    assert [r["k"] for r in out] == [1, 2, 3]
    with pytest.raises(ValueError, match="already exists"):
        spark.sql("CREATE TEMP VIEW tv_agg AS SELECT 1 AS x")
    spark.sql("CREATE OR REPLACE TEMPORARY VIEW tv_agg AS "
              "SELECT k FROM t WHERE k = 1")
    assert spark.sql("SELECT count(*) AS c FROM tv_agg"
                     ).collect().to_pylist()[0]["c"] == 3
    spark.sql("DROP VIEW tv_agg")
    with pytest.raises(Exception):
        spark.sql("SELECT * FROM tv_agg").collect()
    spark.sql("DROP VIEW IF EXISTS tv_agg")
    with pytest.raises(ValueError, match="view not found"):
        spark.sql("DROP VIEW tv_agg")


def test_temp_view_with_cte_body(spark, t):
    spark.sql("CREATE OR REPLACE TEMP VIEW tv_cte AS "
              "WITH c AS (SELECT k FROM t WHERE k > 1) "
              "SELECT count(*) AS c FROM c")
    assert spark.sql("SELECT * FROM tv_cte").collect().to_pylist() == \
        [{"c": 3}]
    spark.sql("DROP VIEW tv_cte")


def test_lateral_view_explode(spark):
    t = pa.table({"k": [1, 2, 3], "arr": [[10, 20], [30], []]})
    spark.create_dataframe(t).createOrReplaceTempView("lv_t")
    out = spark.sql("SELECT k, c FROM lv_t LATERAL VIEW explode(arr) x "
                    "AS c ORDER BY k, c").collect().to_pylist()
    assert out == [{"k": 1, "c": 10}, {"k": 1, "c": 20},
                   {"k": 2, "c": 30}]
    out2 = spark.sql("SELECT k, x.c FROM lv_t LATERAL VIEW OUTER "
                     "explode(arr) x AS c ORDER BY k, c"
                     ).collect().to_pylist()
    assert out2[-1] == {"k": 3, "c": None}
    out3 = spark.sql("SELECT k, p, c FROM lv_t LATERAL VIEW "
                     "posexplode(arr) x AS p, c ORDER BY k, p"
                     ).collect().to_pylist()
    assert out3[:2] == [{"k": 1, "p": 0, "c": 10},
                        {"k": 1, "p": 1, "c": 20}]
    with pytest.raises(ValueError, match="unsupported LATERAL"):
        spark.sql("SELECT 1 FROM lv_t LATERAL VIEW json_tuple(arr) x "
                  "AS a").collect()


def test_lateral_view_then_join_rejected(spark):
    t = pa.table({"k": [1], "arr": [[1]]})
    spark.create_dataframe(t).createOrReplaceTempView("lvj_t")
    spark.create_dataframe(pa.table({"k": [1]})
                           ).createOrReplaceTempView("lvj_u")
    with pytest.raises(ValueError, match="JOIN after LATERAL VIEW"):
        spark.sql("SELECT * FROM lvj_t LATERAL VIEW explode(arr) x AS c "
                  "JOIN lvj_u ON lvj_t.k = lvj_u.k").collect()


def test_tablesample(spark):
    t = pa.table({"k": list(range(10_000))})
    spark.create_dataframe(t).createOrReplaceTempView("ts_t")
    n = spark.sql("SELECT count(*) AS c FROM ts_t TABLESAMPLE (10 PERCENT)"
                  " REPEATABLE (7)").collect().to_pylist()[0]["c"]
    assert 500 < n < 1_500
    n2 = spark.sql("SELECT count(*) AS c FROM ts_t TABLESAMPLE "
                   "(10 PERCENT) REPEATABLE (7)"
                   ).collect().to_pylist()[0]["c"]
    assert n == n2  # deterministic under REPEATABLE
    assert spark.sql("SELECT count(*) AS c FROM ts_t TABLESAMPLE (25 ROWS)"
                     ).collect().to_pylist()[0]["c"] == 25
    # both alias positions
    assert len(spark.sql("SELECT x.k FROM ts_t TABLESAMPLE (5 ROWS) x"
                         ).collect()) == 5
    assert len(spark.sql("SELECT x.k FROM ts_t x TABLESAMPLE (5 ROWS)"
                         ).collect()) == 5
    with pytest.raises(ValueError, match="PERCENT"):
        spark.sql("SELECT 1 FROM ts_t TABLESAMPLE (10 BUCKETS)").collect()
