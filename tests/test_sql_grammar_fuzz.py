"""Grammar fuzz for the SQL front end (sqlparser.py — the repo's largest
file had example-based tests only; VERDICT r3 weak #4).  A type-directed
random generator emits queries over a dialect-common subset and runs the
SAME text through the engine and through stdlib sqlite3 — a genuinely
independent SQL implementation — comparing row sets.

The grammar stays inside semantics both dialects share exactly: integer
(no division, bounded ranges), float64 (no NaN/inf), ASCII strings,
three-valued NULL logic, CASE/COALESCE/NULLIF/IN/BETWEEN/LIKE-free
predicates, COUNT/SUM/MIN/MAX/AVG (+DISTINCT), GROUP BY/HAVING, inner and
left equi-joins, uncorrelated scalar/IN subqueries, UNION ALL, and
ORDER BY with a unique tiebreaker + LIMIT (NULLS FIRST asc / NULLS LAST
desc — both engines' default).
"""

import math
import random
import sqlite3

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt

N1, N2 = 2000, 300


def _make_data(seed=7):
    rng = np.random.default_rng(seed)

    def nullable(arr, frac=0.12):
        mask = rng.random(len(arr)) < frac
        return [None if m else v for m, v in zip(mask, arr.tolist())]

    words = ["alpha", "Beta", "GAMMA", "delta", "Ep", "zeta_9", "", "x"]
    t1 = pa.table({
        "id": pa.array(list(range(N1)), pa.int64()),
        "i": pa.array(nullable(rng.integers(-1000, 1000, N1)), pa.int64()),
        "j": pa.array(rng.integers(0, 20, N1), pa.int64()),
        "f": pa.array(nullable(np.round(rng.standard_normal(N1) * 100, 4)),
                      pa.float64()),
        "s": pa.array(nullable(rng.choice(words, N1), 0.15)),
    })
    t2 = pa.table({
        "k": pa.array(rng.integers(0, 20, N2), pa.int64()),
        "v": pa.array(nullable(np.round(rng.random(N2) * 50, 4)),
                      pa.float64()),
        "s2": pa.array(nullable(rng.choice(words, N2), 0.2)),
    })
    return t1, t2


@pytest.fixture(scope="module")
def engines():
    t1, t2 = _make_data()
    sess = srt.session()
    sess.create_dataframe(t1, num_partitions=3).createOrReplaceTempView("t1")
    sess.create_dataframe(t2).createOrReplaceTempView("t2")
    con = sqlite3.connect(":memory:")
    for name, tbl in (("t1", t1), ("t2", t2)):
        cols = ", ".join(tbl.column_names)
        con.execute(f"CREATE TABLE {name} ({cols})")
        rows = list(zip(*[tbl.column(c).to_pylist()
                          for c in tbl.column_names]))
        ph = ", ".join("?" * tbl.num_columns)
        con.executemany(f"INSERT INTO {name} VALUES ({ph})", rows)
    yield sess, con
    con.close()


# --------------------------------------------------------------------------
# Type-directed expression generator
# --------------------------------------------------------------------------

class Gen:
    """Random expressions with SQL text shared by both dialects.  Types:
    'int', 'float', 'str'; predicates are separate."""

    def __init__(self, rng: random.Random, int_cols, float_cols, str_cols):
        self.rng = rng
        self.cols = {"int": int_cols, "float": float_cols, "str": str_cols}

    def expr(self, t: str, depth: int) -> str:
        r = self.rng
        if depth <= 0 or r.random() < 0.35:
            if self.cols[t] and r.random() < 0.75:
                return r.choice(self.cols[t])
            if t == "int":
                return str(r.randint(-50, 50))
            if t == "float":
                return f"{r.uniform(-20, 20):.3f}"
            return "'" + r.choice(["ab", "Qx", "", "zz9", "Ep"]) + "'"
        d = depth - 1
        if t in ("int", "float"):
            pick = r.random()
            if pick < 0.35:
                op = r.choice(["+", "-"] + (["*"] if t == "float" else []))
                return f"({self.expr(t, d)} {op} {self.expr(t, d)})"
            if pick < 0.45 and t == "int":
                return f"({self.expr(t, d)} * {r.randint(-4, 4)})"
            if pick < 0.60:
                return f"abs({self.expr(t, d)})"
            if pick < 0.72:
                return f"coalesce({self.expr(t, d)}, {self.expr(t, 0)})"
            if pick < 0.82:
                return f"nullif({self.expr(t, d)}, {self.expr(t, 0)})"
            if pick < 0.92:
                return (f"(CASE WHEN {self.pred(d)} THEN {self.expr(t, d)} "
                        f"ELSE {self.expr(t, d)} END)")
            if t == "int":
                return f"length({self.expr('str', d)})"
            return f"({self.expr('float', d)} * 0.5)"
        # strings
        pick = r.random()
        if pick < 0.25:
            return f"upper({self.expr('str', d)})"
        if pick < 0.50:
            return f"lower({self.expr('str', d)})"
        if pick < 0.68:
            return (f"substr({self.expr('str', d)}, "
                    f"{r.randint(1, 3)}, {r.randint(1, 4)})")
        if pick < 0.84:
            return f"({self.expr('str', d)} || {self.expr('str', d)})"
        return (f"(CASE WHEN {self.pred(d)} THEN {self.expr('str', d)} "
                f"ELSE {self.expr('str', d)} END)")

    def pred(self, depth: int) -> str:
        r = self.rng
        if depth <= 0 or r.random() < 0.4:
            t = r.choice(["int", "float", "str"])
            a = self.expr(t, max(depth - 1, 0))
            pick = r.random()
            if pick < 0.15:
                return f"({a} IS {'NOT ' if r.random() < 0.5 else ''}NULL)"
            if pick < 0.35 and t != "str":
                lo = r.randint(-100, 0)
                return f"({a} BETWEEN {lo} AND {lo + r.randint(1, 150)})"
            if pick < 0.5 and t == "int":
                lits = ", ".join(str(r.randint(-20, 20))
                                 for _ in range(r.randint(1, 5)))
                return f"({a} {'NOT ' if r.random() < 0.3 else ''}IN ({lits}))"
            op = r.choice(["<", "<=", ">", ">=", "=", "<>"])
            return f"({a} {op} {self.expr(t, max(depth - 1, 0))})"
        d = depth - 1
        pick = r.random()
        if pick < 0.45:
            return f"({self.pred(d)} AND {self.pred(d)})"
        if pick < 0.85:
            return f"({self.pred(d)} OR {self.pred(d)})"
        return f"(NOT {self.pred(d)})"

    def agg(self, t: str, depth: int) -> str:
        """Includes DISTINCT mixed with plain aggregates and across
        different child sets — the engine's Expand-distinct path
        (planner._plan_expand_distinct) covers those."""
        r = self.rng
        pick = r.random()
        e = self.expr(t, depth)
        if pick < 0.15:
            return "count(*)"
        if pick < 0.3:
            return f"count({e})"
        if pick < 0.42:
            d = r.choice(self.cols[t]) if (self.cols[t]
                                           and r.random() < 0.6) else e
            return f"count(DISTINCT {d})"
        if pick < 0.58 and t != "str":
            return f"sum({e})"
        if pick < 0.74:
            return f"min({e})"
        if pick < 0.9:
            return f"max({e})"
        if t != "str":
            return f"avg({e})"
        return f"count({e})"


# --------------------------------------------------------------------------
# Comparison
# --------------------------------------------------------------------------

def _norm(v):
    if v is None:
        return (1, "")
    if isinstance(v, bool):
        return (0, int(v))
    if isinstance(v, float):
        if math.isnan(v):
            return (1, "")
        return (0, round(v, 5))
    return (0, v)


def _rows(cols):
    return [tuple(_norm(v) for v in row) for row in zip(*cols)]


def _run_both(engines, sql, ordered=False):
    sess, con = engines
    got_tbl = sess.sql(sql).collect()
    got = _rows([got_tbl.column(i).to_pylist()
                 for i in range(got_tbl.num_columns)])
    want = [tuple(_norm(v) for v in row) for row in con.execute(sql)]
    if not ordered:
        got, want = sorted(got), sorted(want)
    assert len(got) == len(want), f"{len(got)} != {len(want)} rows\n{sql}"
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            if isinstance(a[1], float) or isinstance(b[1], float):
                assert a[0] == b[0] and math.isclose(
                    a[1] or 0.0, b[1] or 0.0,
                    rel_tol=1e-6, abs_tol=1e-6), f"{g} != {w}\n{sql}"
            else:
                assert a == b, f"{g} != {w}\n{sql}"


# --------------------------------------------------------------------------
# Fuzz tiers
# --------------------------------------------------------------------------

def test_project_filter_fuzz(engines):
    rng = random.Random(101)
    g = Gen(rng, ["i", "j", "id"], ["f"], ["s"])
    for q in range(30):
        nsel = rng.randint(1, 4)
        sels = ", ".join(
            f"{g.expr(rng.choice(['int', 'float', 'str']), 3)} AS c{k}"
            for k in range(nsel))
        sql = f"SELECT {sels} FROM t1 WHERE {g.pred(3)}"
        _run_both(engines, sql)


def test_group_agg_having_fuzz(engines):
    rng = random.Random(202)
    g = Gen(rng, ["i", "j"], ["f"], ["s"])
    for q in range(25):
        key = rng.choice(["j", "s", "(i * 2)", "substr(s, 1, 1)",
                          "(j + 1)"])
        if rng.random() < 0.2:
            # distinct-only shape (the engine's supported DISTINCT form)
            col = rng.choice(["i", "j", "s"])
            aggs = f"count(DISTINCT {col}) AS a0"
            key = rng.choice(["j", "s"])
        else:
            aggs = ", ".join(
                f"{g.agg(rng.choice(['int', 'float', 'str']), 2)} AS a{k}"
                for k in range(rng.randint(1, 3)))
        sql = f"SELECT {key} AS k0, {aggs} FROM t1"
        if rng.random() < 0.6:
            sql += f" WHERE {g.pred(2)}"
        sql += f" GROUP BY {key}"
        if rng.random() < 0.4:
            sql += f" HAVING count(*) > {rng.randint(0, 30)}"
        _run_both(engines, sql)


def test_join_fuzz(engines):
    rng = random.Random(303)
    ga = Gen(rng, ["a.i", "a.j"], ["a.f"], ["a.s"])
    gb = Gen(rng, ["b.k"], ["b.v"], ["b.s2"])
    gboth = Gen(rng, ["a.i", "a.j", "b.k"], ["a.f", "b.v"], ["a.s", "b.s2"])
    for q in range(20):
        jt = rng.choice(["JOIN", "LEFT JOIN"])
        on = "a.j = b.k"
        if rng.random() < 0.4:
            on += f" AND {gb.pred(1)}"
        sels = ", ".join(
            f"{gboth.expr(rng.choice(['int', 'float', 'str']), 2)} AS c{k}"
            for k in range(rng.randint(1, 3)))
        sql = f"SELECT {sels} FROM t1 a {jt} t2 b ON {on}"
        if rng.random() < 0.5:
            sql += f" WHERE {ga.pred(2)}"
        _run_both(engines, sql)


def test_subquery_union_fuzz(engines):
    rng = random.Random(404)
    g = Gen(rng, ["i", "j"], ["f"], ["s"])
    for q in range(15):
        shape = rng.random()
        if shape < 0.4:
            inner = rng.choice(["(SELECT max(j) FROM t1)",
                                "(SELECT min(k) FROM t2)",
                                "(SELECT count(*) FROM t2)",
                                "(SELECT avg(k) FROM t2)"])
            sql = (f"SELECT i, j FROM t1 WHERE j > {inner} "
                   f"AND {g.pred(2)}")
        elif shape < 0.7:
            sql = (f"SELECT i FROM t1 WHERE j IN "
                   f"(SELECT k FROM t2 WHERE {Gen(rng, ['k'], ['v'], ['s2']).pred(1)})")
        else:
            e1 = g.expr("int", 2)
            e2 = g.expr("int", 2)
            sql = (f"SELECT {e1} AS c FROM t1 WHERE {g.pred(1)} "
                   f"UNION ALL SELECT {e2} AS c FROM t1 WHERE {g.pred(1)}")
        _run_both(engines, sql)


def test_order_limit_fuzz(engines):
    rng = random.Random(505)
    g = Gen(rng, ["i", "j"], ["f"], ["s"])
    for q in range(15):
        e = g.expr(rng.choice(["int", "str"]), 2)
        direction = rng.choice(["ASC", "DESC"])
        sql = (f"SELECT id, {e} AS c FROM t1 WHERE {g.pred(2)} "
               f"ORDER BY c {direction}, id LIMIT {rng.randint(1, 40)}")
        _run_both(engines, sql, ordered=True)
