"""WHERE EXISTS / IN subquery predicates — rewritten to semi/anti joins
(Spark's RewritePredicateSubquery; the reference runs the resulting
semi/anti joins on GpuHashJoin).  Oracles: pandas."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt


@pytest.fixture()
def rig():
    rng = np.random.default_rng(5)
    n = 3000
    orders = pa.table({"o_key": np.arange(n // 4),
                       "o_flag": rng.integers(0, 2, n // 4)})
    items = pa.table({"i_okey": rng.integers(0, n // 2, n),
                      "i_v": rng.random(n)})
    sess = srt.session()
    sess.create_dataframe(orders).createOrReplaceTempView("sq_orders")
    sess.create_dataframe(items).createOrReplaceTempView("sq_items")
    return sess, orders.to_pandas(), items.to_pandas()


def test_in_subquery(rig):
    sess, po, pi = rig
    got = sess.sql(
        "SELECT o_key FROM sq_orders WHERE o_key IN "
        "(SELECT i_okey FROM sq_items WHERE i_v > 0.9)"
    ).collect().to_pandas()
    keys = set(pi.i_okey[pi.i_v > 0.9])
    assert set(got["o_key"]) == set(po.o_key[po.o_key.isin(keys)])


def test_not_in_subquery_null_aware(rig):
    sess, po, pi = rig
    got = sess.sql("SELECT o_key FROM sq_orders WHERE o_key NOT IN "
                   "(SELECT i_okey FROM sq_items)").collect().to_pandas()
    assert set(got["o_key"]) == set(po.o_key[~po.o_key.isin(set(pi.i_okey))])
    # any NULL in the subquery result -> 3-valued logic filters every row
    sess.create_dataframe(pa.table(
        {"x": pa.array([1, None, 2], type=pa.int64())})
    ).createOrReplaceTempView("sq_nulls")
    got = sess.sql("SELECT o_key FROM sq_orders WHERE o_key NOT IN "
                   "(SELECT x FROM sq_nulls)").collect()
    assert got.num_rows == 0


def test_correlated_exists_and_not_exists(rig):
    sess, po, pi = rig
    got = sess.sql(
        "SELECT o_key FROM sq_orders o WHERE EXISTS (SELECT 1 FROM "
        "sq_items i WHERE i.i_okey = o.o_key AND i.i_v > 0.95)"
    ).collect().to_pandas()
    keys = set(pi.i_okey[pi.i_v > 0.95])
    assert set(got["o_key"]) == set(po.o_key[po.o_key.isin(keys)])
    got = sess.sql(
        "SELECT o_key FROM sq_orders o WHERE NOT EXISTS (SELECT 1 FROM "
        "sq_items i WHERE i.i_okey = o.o_key)").collect().to_pandas()
    assert set(got["o_key"]) == set(po.o_key[~po.o_key.isin(set(pi.i_okey))])


def test_uncorrelated_exists_gates_whole_result(rig):
    sess, po, pi = rig
    got = sess.sql("SELECT o_key FROM sq_orders WHERE o_flag = 1 AND "
                   "EXISTS (SELECT 1 FROM sq_items WHERE i_v > 2.0)"
                   ).collect()
    assert got.num_rows == 0
    got = sess.sql("SELECT o_key FROM sq_orders WHERE o_flag = 1 AND "
                   "EXISTS (SELECT 1 FROM sq_items WHERE i_v > 0.5)"
                   ).collect()
    assert got.num_rows == int((po.o_flag == 1).sum())


def test_subquery_under_or(rig):
    """IN under OR takes the embedded existence-join rewrite (it predates
    this test's old expectation of a parse rejection)."""
    sess, po, pi = rig
    got = sess.sql("SELECT o_key FROM sq_orders WHERE o_flag = 1 OR "
                   "o_key IN (SELECT i_okey FROM sq_items)"
                   ).collect().to_pandas()
    keys = set(pi.i_okey)
    exp = po.o_key[(po.o_flag == 1) | po.o_key.isin(keys)]
    assert set(got["o_key"]) == set(exp)


def test_not_in_empty_subquery_keeps_null_needle(rig):
    sess, _, _ = rig
    sess.create_dataframe(pa.table(
        {"x": pa.array([1, None, 5], type=pa.int64())})
    ).createOrReplaceTempView("sq_t3")
    sess.create_dataframe(pa.table(
        {"y": pa.array([], type=pa.int64())})
    ).createOrReplaceTempView("sq_empty")
    # IN over the empty set is FALSE (not NULL) even for a null needle,
    # so NOT IN keeps every row
    got = sess.sql("SELECT x FROM sq_t3 WHERE x NOT IN "
                   "(SELECT y FROM sq_empty)").collect()
    assert got.num_rows == 3


def test_correlated_exists_limit_semantics(rig):
    sess, _, _ = rig
    sess.create_dataframe(pa.table(
        {"k": pa.array([1, 2, 3], type=pa.int64())})
    ).createOrReplaceTempView("sq_o2")
    sess.create_dataframe(pa.table(
        {"ik": pa.array([1, 1, 3], type=pa.int64())})
    ).createOrReplaceTempView("sq_i2")
    # LIMIT n>0 inside EXISTS is per-outer-row, i.e. a no-op
    got = sess.sql("SELECT k FROM sq_o2 WHERE EXISTS (SELECT 1 FROM "
                   "sq_i2 WHERE sq_i2.ik = sq_o2.k LIMIT 1)"
                   ).collect().to_pylist()
    assert sorted(r["k"] for r in got) == [1, 3]
    got = sess.sql("SELECT k FROM sq_o2 WHERE EXISTS (SELECT 1 FROM "
                   "sq_i2 WHERE sq_i2.ik = sq_o2.k LIMIT 0)").collect()
    assert got.num_rows == 0
    with pytest.raises(ValueError, match="GROUP BY"):
        sess.sql("SELECT k FROM sq_o2 WHERE EXISTS (SELECT ik FROM "
                 "sq_i2 WHERE sq_i2.ik = sq_o2.k GROUP BY ik)").collect()


def test_scalar_subquery(rig):
    sess, _, pi = rig
    got = sess.sql("SELECT i_okey FROM sq_items WHERE i_v > "
                   "(SELECT avg(i_v) FROM sq_items)").collect()
    assert got.num_rows == int((pi.i_v > pi.i_v.mean()).sum())
    row = sess.sql("SELECT (SELECT max(i_v) FROM sq_items) AS mx "
                   "FROM sq_orders LIMIT 1").collect().to_pylist()[0]
    assert np.isclose(row["mx"], pi.i_v.max())
    # empty result -> NULL; multiple rows -> error
    row = sess.sql("SELECT (SELECT max(i_v) FROM sq_items WHERE i_v > 2) "
                   "AS m FROM sq_orders LIMIT 1").collect().to_pylist()[0]
    assert row["m"] is None
    with pytest.raises(ValueError, match="more than one row"):
        sess.sql("SELECT (SELECT i_v FROM sq_items) FROM sq_orders"
                 ).collect()


def test_subquery_guards_and_self_correlation(rig):
    sess, _, _ = rig
    sess.create_dataframe(pa.table(
        {"k": pa.array([1, 2, 3], type=pa.int64())})
    ).createOrReplaceTempView("sq_o3")
    sess.create_dataframe(pa.table(
        {"ik": pa.array([1, 1, 3], type=pa.int64())})
    ).createOrReplaceTempView("sq_i3")
    with pytest.raises(ValueError, match="OFFSET"):
        sess.sql("SELECT k FROM sq_o3 WHERE EXISTS (SELECT 1 FROM sq_i3 "
                 "WHERE sq_i3.ik = sq_o3.k LIMIT 1 OFFSET 1)").collect()
    with pytest.raises(ValueError, match="not supported in the"):
        sess.sql("SELECT EXISTS(SELECT 1 FROM sq_i3) AS e FROM sq_o3"
                 ).collect()
    # unaliased outer name stays visible when the inner aliases the same
    # table (SQL scoping: an alias hides the base name)
    got = sess.sql("SELECT k FROM sq_o3 WHERE EXISTS (SELECT 1 FROM "
                   "sq_o3 l2 WHERE sq_o3.k = l2.k)").collect()
    assert got.num_rows == 3


def test_correlated_scalar_and_grouping_sets_guards(rig):
    sess, _, _ = rig
    sess.create_dataframe(pa.table(
        {"k": pa.array([1, 2], type=pa.int64()), "v": [1.0, 2.0]})
    ).createOrReplaceTempView("sq_out")
    sess.create_dataframe(pa.table(
        {"ik": pa.array([1, 2], type=pa.int64()), "iv": [5.0, 6.0]})
    ).createOrReplaceTempView("sq_in2")
    # round 3: this shape decorrelates into a grouped-agg LEFT JOIN
    out = sess.sql("SELECT k FROM sq_out WHERE v > (SELECT max(iv) FROM "
                   "sq_in2 WHERE sq_in2.ik = sq_out.k)").collect()
    assert out.num_rows == 0  # v (1,2) never exceeds max(iv) (5,6)
    with pytest.raises(ValueError, match="not supported in the"):
        sess.sql("SELECT count(*) FROM sq_out GROUP BY GROUPING SETS "
                 "((k), (EXISTS(SELECT 1 FROM sq_in2)))").collect()


# --- correlated scalar subqueries (RewriteCorrelatedScalarSubquery) --------

def test_correlated_scalar_avg_in_where(session):
    """TPC-H q17 shape: v < (SELECT 0.2*avg(x) FROM t2 WHERE t2.k = t.k)."""
    rng = np.random.default_rng(3)
    n = 20_000
    li = pa.table({"partkey": rng.integers(0, 200, n),
                   "quantity": rng.integers(1, 50, n).astype(np.float64),
                   "price": rng.random(n) * 100})
    session.create_dataframe(li, num_partitions=3) \
        .createOrReplaceTempView("li17")
    got = session.sql(
        "SELECT sum(l.price) AS rev FROM li17 l "
        "WHERE l.quantity < (SELECT 0.2 * avg(l2.quantity) FROM li17 l2 "
        "WHERE l2.partkey = l.partkey)").collect().to_pylist()[0]["rev"]
    pdf = li.to_pandas()
    th = pdf.groupby("partkey").quantity.mean() * 0.2
    exp = pdf[pdf.quantity < pdf.partkey.map(th)].price.sum()
    assert abs(got - exp) < 1e-6 * max(abs(exp), 1)


def test_correlated_scalar_in_select_list_and_count_bug(session):
    session.create_dataframe(pa.table({"k": [1, 2, 3], "v": [10., 20., 30.]})
                           ).createOrReplaceTempView("ca")
    session.create_dataframe(pa.table({"k": [1, 1, 2], "w": [5., 7., 9.]})
                           ).createOrReplaceTempView("cb")
    out = session.sql(
        "SELECT ca.k, (SELECT count(*) FROM cb WHERE cb.k = ca.k) AS c, "
        "(SELECT sum(cb.w) FROM cb WHERE cb.k = ca.k) AS s "
        "FROM ca ORDER BY ca.k").collect().to_pylist()
    # k=3 has NO rows in cb: count must be 0 (the COUNT bug), sum NULL
    assert out == [{"k": 1, "c": 2, "s": 12.0},
                   {"k": 2, "c": 1, "s": 9.0},
                   {"k": 3, "c": 0, "s": None}]


def test_correlated_scalar_rejects_unsupported_shapes(session):
    session.create_dataframe(pa.table({"k": [1], "v": [1.0]})
                           ).createOrReplaceTempView("cs1")
    session.create_dataframe(pa.table({"k": [1], "w": [2.0]})
                           ).createOrReplaceTempView("cs2")
    with pytest.raises(Exception, match="must be an aggregate"):
        session.sql("SELECT (SELECT cs2.w FROM cs2 WHERE cs2.k = cs1.k)"
                    " FROM cs1").collect()
    with pytest.raises(Exception, match="equality"):
        session.sql("SELECT (SELECT max(cs2.w) FROM cs2 WHERE "
                    "cs2.k > cs1.k) FROM cs1").collect()
    with pytest.raises(Exception, match="compound"):
        session.sql("SELECT (SELECT count(*) + 1 FROM cs2 WHERE cs2.k ="
                    " cs1.k) FROM cs1").collect()


def test_correlated_scalar_star_and_naming_and_dedup(session):
    """SELECT * must not leak the decorrelation join's internal columns;
    an unaliased subquery column is named scalarsubquery(); identical
    subqueries share one join (ReuseSubquery analog)."""
    session.create_dataframe(pa.table({"k": [1, 2, 3], "v": [10., 20., 30.]})
                             ).createOrReplaceTempView("da")
    session.create_dataframe(pa.table({"k": [1, 1, 2], "w": [5., 7., 9.]})
                             ).createOrReplaceTempView("db")
    out = session.sql(
        "SELECT * FROM da WHERE da.v > "
        "(SELECT sum(db.w) FROM db WHERE db.k = da.k)").collect()
    assert out.column_names == ["k", "v"]
    out2 = session.sql(
        "SELECT (SELECT max(db.w) FROM db WHERE db.k = da.k) FROM da"
    ).collect()
    assert out2.column_names == ["scalarsubquery()"]
    out3 = session.sql(
        "SELECT da.k, (SELECT sum(db.w) FROM db WHERE db.k = da.k) AS s "
        "FROM da WHERE (SELECT sum(db.w) FROM db WHERE db.k = da.k) > 10 "
        "ORDER BY da.k").collect().to_pylist()
    assert out3 == [{"k": 1, "s": 12.0}]
    with pytest.raises(ValueError, match="join condition"):
        session.sql(
            "SELECT da.k FROM da JOIN db ON da.v = "
            "(SELECT avg(db.w) FROM db WHERE db.k = da.k)").collect()


def test_embedded_correlated_exists_limit_zero(session):
    """Embedded (under OR) correlated EXISTS with LIMIT 0: the subquery is
    per-outer-row empty, so the marker must be constant FALSE — the
    rewrite used to drop the LIMIT and return [10, 40] where Spark
    returns [40] (ADVICE r5, sqlparser.py:2173)."""
    session.create_dataframe(pa.table(
        {"k": pa.array([1, 2], type=pa.int64()),
         "v": pa.array([10, 40], type=pa.int64())})
    ).createOrReplaceTempView("el_o")
    session.create_dataframe(pa.table(
        {"ik": pa.array([1, 1], type=pa.int64())})
    ).createOrReplaceTempView("el_i")
    got = session.sql(
        "SELECT v FROM el_o WHERE v = 40 OR EXISTS (SELECT 1 FROM el_i "
        "WHERE el_i.ik = el_o.k LIMIT 0)").collect().to_pylist()
    assert sorted(r["v"] for r in got) == [40]
    # LIMIT n>0 stays a no-op for EXISTS
    got = session.sql(
        "SELECT v FROM el_o WHERE v = 40 OR EXISTS (SELECT 1 FROM el_i "
        "WHERE el_i.ik = el_o.k LIMIT 1)").collect().to_pylist()
    assert sorted(r["v"] for r in got) == [10, 40]


def test_embedded_correlated_exists_offset_rejected(session):
    session.create_dataframe(pa.table(
        {"k": pa.array([1], type=pa.int64()),
         "v": pa.array([10], type=pa.int64())})
    ).createOrReplaceTempView("eo_o")
    session.create_dataframe(pa.table(
        {"ik": pa.array([1], type=pa.int64())})
    ).createOrReplaceTempView("eo_i")
    with pytest.raises(ValueError, match="OFFSET"):
        session.sql(
            "SELECT v FROM eo_o WHERE v = 40 OR EXISTS (SELECT 1 FROM "
            "eo_i WHERE eo_i.ik = eo_o.k LIMIT 1 OFFSET 1)").collect()
