"""String expression tests — device (jitted jnp) vs host (numpy) backends vs
a pure-Python oracle (reference model: ``integration_tests/src/main/python/
string_test.py`` CPU-vs-GPU comparisons)."""

import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.expressions import strings as S
from spark_rapids_tpu.sql.expressions.core import (AttributeReference,
                                                   Literal)
import spark_rapids_tpu.types as T

from test_expressions import eval_both, make_batch, to_host_batch


def eval_host(expr, table):
    """Host-engine-only evaluation, for expressions tagged host-only (the
    planner never jits these; tag_for_device routes them to CPU)."""
    import numpy as np
    from spark_rapids_tpu.columnar import device_column_to_arrow
    from spark_rapids_tpu.sql.expressions.core import (EvalContext,
                                                       bind_references)
    batch = to_host_batch(make_batch(table))
    attrs = [AttributeReference(n, c.dtype)
             for n, c in zip(batch.names, batch.columns)]
    bound = bind_references(expr, attrs)
    assert bound.tag_for_device(), "host-only expr must self-tag"
    col = bound.eval(EvalContext(batch, xp=np))
    return device_column_to_arrow(col, table.num_rows).to_pylist()

STRS = ["hello world", "", "  padded  ", "UPPER lower", "héllo wörld",
        "a,b,,c,d", "日本語テキスト", "x", None, "the quick brown fox",
        "aaa", "ab" * 20]


def tbl(vals=STRS, name="s"):
    return pa.table({name: pa.array(vals, type=pa.string())})


def s_attr(name="s"):
    return AttributeReference(name, T.STRING)


def oracle(fn, vals=STRS):
    return [None if v is None else fn(v) for v in vals]


class TestMeasures:
    def test_length(self):
        assert eval_both(S.Length(s_attr()), tbl()) == oracle(len)

    def test_octet_length(self):
        assert eval_both(S.OctetLength(s_attr()), tbl()) == \
            oracle(lambda s: len(s.encode()))

    def test_bit_length(self):
        assert eval_both(S.BitLength(s_attr()), tbl()) == \
            oracle(lambda s: 8 * len(s.encode()))


class TestTransforms:
    def test_upper_ascii(self):
        got = eval_both(S.Upper(s_attr()), tbl())
        exp = oracle(lambda s: "".join(
            c.upper() if c.isascii() else c for c in s))
        assert got == exp

    def test_lower_ascii(self):
        got = eval_both(S.Lower(s_attr()), tbl())
        exp = oracle(lambda s: "".join(
            c.lower() if c.isascii() else c for c in s))
        assert got == exp

    def test_reverse_utf8(self):
        assert eval_both(S.Reverse(s_attr()), tbl()) == \
            oracle(lambda s: s[::-1])

    def test_initcap(self):
        vals = ["hello world", "FOO bar", "", " x", "a  b"]
        got = eval_both(S.InitCap(s_attr()), tbl(vals))
        assert got == ["Hello World", "Foo Bar", "", " X", "A  B"]


class TestSubstring:
    @pytest.mark.parametrize("pos,ln", [(1, 3), (3, 100), (0, 2), (-3, 2),
                                        (-100, 3), (5, 0), (2, None)])
    def test_substring(self, pos, ln):
        e = S.Substring(s_attr(), Literal(pos),
                        None if ln is None else Literal(ln))

        def exp(s):
            # UTF8String.substringSQL semantics
            n = len(s)
            start = pos - 1 if pos > 0 else (n + pos if pos < 0 else 0)
            end = n if ln is None else min(start + max(ln, 0), 2 ** 30)
            start_c = max(start, 0)
            return s[start_c:max(end, start_c)] if end > 0 else ""

        assert eval_both(e, tbl()) == oracle(exp)

    @pytest.mark.parametrize("count", [1, 2, -1, -2, 0, 10])
    def test_substring_index(self, count):
        vals = ["a.b.c.d", "abc", ".x.", "", "..", None]
        e = S.SubstringIndex(s_attr(), Literal("."), Literal(count))

        def exp(s):
            if count == 0:
                return ""
            parts = s.split(".")
            if count > 0:
                return s if count >= len(parts) else ".".join(parts[:count])
            return s if -count >= len(parts) else ".".join(parts[count:])

        assert eval_both(e, tbl(vals)) == oracle(exp, vals)


class TestConcat:
    def test_concat(self):
        t = pa.table({"a": ["x", "yy", None, ""],
                      "b": ["1", None, "2", "33"]})
        e = S.Concat(AttributeReference("a", T.STRING),
                     AttributeReference("b", T.STRING))
        assert eval_both(e, t) == ["x1", None, None, "33"]

    def test_concat_ws_skips_nulls(self):
        t = pa.table({"a": ["x", None, None, "q"],
                      "b": ["y", "z", None, None]})
        e = S.ConcatWs(Literal("-"), AttributeReference("a", T.STRING),
                       AttributeReference("b", T.STRING))
        assert eval_both(e, t) == ["x-y", "z", "", "q"]


class TestPredicates:
    def test_contains(self):
        e = S.Contains(s_attr(), Literal("lo"))
        assert eval_both(e, tbl()) == oracle(lambda s: "lo" in s)

    def test_starts_ends(self):
        assert eval_both(S.StartsWith(s_attr(), Literal("he")), tbl()) == \
            oracle(lambda s: s.startswith("he"))
        assert eval_both(S.EndsWith(s_attr(), Literal("ld")), tbl()) == \
            oracle(lambda s: s.endswith("ld"))

    @pytest.mark.parametrize("pat,rx", [
        ("hello%", r"hello.*"), ("%world", r".*world"), ("%lo w%", r".*lo w.*"),
        ("h_llo%", r"h.llo.*"), ("x", r"x"), ("%", r".*"), ("", r""),
        ("_____", r"....."), ("a%b%c", r"a.*b.*c."[:-1]),
    ])
    def test_like(self, pat, rx):
        import re
        vals = [v for v in STRS if v is None or v.isascii()]
        e = S.Like(s_attr(), Literal(pat))
        exp = oracle(lambda s: re.fullmatch(rx, s, re.DOTALL) is not None,
                     vals)
        assert eval_both(e, tbl(vals)) == exp


class TestSearch:
    def test_instr(self):
        e = S.StringInstr(s_attr(), Literal("o"))
        assert eval_both(e, tbl()) == oracle(lambda s: s.find("o") + 1)

    def test_instr_utf8_position(self):
        # instr returns CHARACTER positions on multi-byte strings
        vals = ["日本語テキスト", "héllo"]
        e = S.StringInstr(s_attr(), Literal("語"))
        assert eval_both(e, tbl(vals)) == [3, 0]

    @pytest.mark.parametrize("start", [1, 3, 0])
    def test_locate(self, start):
        e = S.StringLocate(Literal("o"), s_attr(), Literal(start))

        def exp(s):
            if start <= 0:
                return 0
            return s.find("o", start - 1) + 1

        assert eval_both(e, tbl()) == oracle(exp)


class TestEditing:
    def test_replace(self):
        e = S.StringReplace(s_attr(), Literal("o"), Literal("0"))
        assert eval_both(e, tbl()) == oracle(lambda s: s.replace("o", "0"))

    def test_replace_grow(self):
        e = S.StringReplace(s_attr(), Literal("l"), Literal("LLL"))
        assert eval_both(e, tbl()) == oracle(lambda s: s.replace("l", "LLL"))

    def test_replace_empty_search_is_noop(self):
        e = S.StringReplace(s_attr(), Literal(""), Literal("X"))
        assert eval_both(e, tbl()) == oracle(lambda s: s)

    def test_translate(self):
        e = S.StringTranslate(s_attr(), Literal("lo"), Literal("01"))
        assert eval_both(e, tbl()) == \
            oracle(lambda s: s.translate(str.maketrans("lo", "01")))

    def test_translate_delete(self):
        e = S.StringTranslate(s_attr(), Literal("aeiou"), Literal(""))
        assert eval_both(e, tbl()) == \
            oracle(lambda s: s.translate(str.maketrans("", "", "aeiou")))

    @pytest.mark.parametrize("n", [0, 1, 3])
    def test_repeat(self, n):
        e = S.StringRepeat(s_attr(), Literal(n))
        assert eval_both(e, tbl()) == oracle(lambda s: s * n)

    @pytest.mark.parametrize("left", [True, False])
    def test_pad(self, left):
        cls = S.StringLPad if left else S.StringRPad
        e = cls(s_attr(), Literal(8), Literal("*-"))
        vals = ["abc", "", "12345678", "123456789x"]

        def exp(s):
            if len(s) >= 8:
                return s[:8]
            pad = ("*-" * 8)[:8 - len(s)]
            return pad + s if left else s + pad

        assert eval_both(e, tbl(vals)) == oracle(exp, vals)

    def test_trim_family(self):
        vals = ["  hi  ", "xxhixx", "hi", "   ", ""]
        assert eval_both(S.StringTrim(s_attr()), tbl(vals)) == \
            oracle(lambda s: s.strip(" "), vals)
        assert eval_both(S.StringTrimLeft(s_attr()), tbl(vals)) == \
            oracle(lambda s: s.lstrip(" "), vals)
        assert eval_both(S.StringTrimRight(s_attr()), tbl(vals)) == \
            oracle(lambda s: s.rstrip(" "), vals)
        assert eval_both(S.StringTrim(s_attr(), Literal("x")), tbl(vals)) == \
            oracle(lambda s: s.strip("x"), vals)


class TestHostTail:
    def test_format_number(self):
        t = pa.table({"x": pa.array([1234567.891, 0.5, -42.0, None])})
        e = S.FormatNumber(AttributeReference("x", T.DOUBLE), Literal(2))
        assert eval_host(e, t) == ["1,234,567.89", "0.50", "-42.00", None]

    def test_conv(self):
        # Spark NumberConverter: '-' folds through unsigned 64-bit when
        # to_base > 0; invalid prefixes parse their leading digits; no
        # digits at all -> NULL
        t = pa.table({"s": ["255", "ff", "-10", None, "11abc", "zz"]})
        got = eval_host(S.Conv(s_attr(), Literal(16), Literal(10)), t)
        assert got == ["597", "255", "18446744073709551600", None, "72380",
                       None]

    def test_conv_signed_output_and_prefix(self):
        t = pa.table({"s": ["11abc", "-11"]})
        # to_base=16: leading digits parse, negative wraps unsigned;
        # to_base=-16: negative renders signed
        assert eval_host(S.Conv(s_attr(), Literal(10), Literal(16)), t) == \
            ["B", "FFFFFFFFFFFFFFF5"]
        assert eval_host(S.Conv(s_attr(), Literal(10), Literal(-16)), t) == \
            ["B", "-B"]

    def test_md5(self):
        import hashlib
        e = S.Md5(s_attr())
        vals = ["abc", "", "hello"]
        assert eval_host(e, tbl(vals)) == \
            oracle(lambda s: hashlib.md5(s.encode()).hexdigest(), vals)


class TestDataFrameIntegration:
    def test_string_pipeline(self):
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.sql import functions as F
        s = srt.session()
        df = s.create_dataframe({"name": ["alice", "BOB", "  carol "],
                                 "city": ["nyc", "sf", None]})
        out = df.select(
            F.upper(F.col("name")).alias("u"),
            F.concat_ws("/", F.trim(F.col("name")), F.col("city")).alias("j"),
            F.length(F.col("name")).alias("n"),
        ).collect()
        assert out.column("u").to_pylist() == ["ALICE", "BOB", "  CAROL "]
        assert out.column("j").to_pylist() == ["alice/nyc", "BOB/sf", "carol"]
        assert out.column("n").to_pylist() == [5, 3, 8]

    def test_filter_on_like(self):
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.sql import functions as F
        s = srt.session()
        df = s.create_dataframe({"s": ["apple", "banana", "cherry", "avocado"]})
        out = df.filter(F.like(F.col("s"), "a%")).collect()
        assert out.column("s").to_pylist() == ["apple", "avocado"]
