"""Cross-process TCP shuffle transport + driver heartbeat registry
(reference RapidsShuffleClient/Server + RapidsShuffleHeartbeatManager;
tested at the SPI seam like the reference's transport suites, plus one
genuine two-process block fetch)."""

import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.shuffle.tcp import (TcpHeartbeatClient,
                                          TcpHeartbeatServer,
                                          TcpShuffleTransport)
from spark_rapids_tpu.shuffle.transport import BlockId, PeerInfo


def test_tcp_fetch_between_transports():
    a = TcpShuffleTransport("exec-a")
    b = TcpShuffleTransport("exec-b")
    try:
        blk = BlockId(1, 0, 3)
        a.publish("exec-a", blk, b"hello-shuffle-frame")
        peer_a = PeerInfo("exec-a", a.endpoint)
        assert b.fetch(peer_a, blk) == b"hello-shuffle-frame"
        assert b.fetch(peer_a, BlockId(1, 0, 4)) is None
        # own blocks short-circuit to the local store
        b.publish("exec-b", BlockId(2, 1, 1), b"mine")
        assert b.fetch(PeerInfo("exec-b", b.endpoint),
                       BlockId(2, 1, 1)) == b"mine"
        # connection reuse: many sequential fetches on one socket
        for i in range(20):
            a.publish("exec-a", BlockId(3, i, 0), bytes([i]) * (i + 1))
        for i in range(20):
            assert b.fetch(peer_a, BlockId(3, i, 0)) == bytes([i]) * (i + 1)
    finally:
        a.close()
        b.close()


def test_heartbeat_registry_discovery_and_expiry():
    srv = TcpHeartbeatServer(heartbeat_timeout_s=0.3)
    try:
        c1 = TcpHeartbeatClient(srv.endpoint)
        c2 = TcpHeartbeatClient(srv.endpoint)
        assert c1.register("e1", "127.0.0.1:1111") == []
        peers = c2.register("e2", "127.0.0.1:2222")
        assert [p.executor_id for p in peers] == ["e1"]
        peers = c1.heartbeat("e1")
        assert [p.executor_id for p in peers] == ["e2"]
        # e2 stops heartbeating -> expires
        time.sleep(0.4)
        peers = c1.heartbeat("e1")
        assert [p.executor_id for p in peers] == []
        c1.close()
        c2.close()
    finally:
        srv.close()


def test_manager_cross_executor_fetch_via_discovery():
    """Two shuffle managers in one process, separate TCP transports and a
    shared registry: B reads a reduce partition whose blocks live on A."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.columnar.convert import arrow_to_device
    from spark_rapids_tpu.shuffle.manager import ShuffleManager

    srv = TcpHeartbeatServer()
    try:
        conf = srt.RapidsConf.get_global().copy({
            "spark.rapids.shuffle.mode": "ICI",
            "spark.rapids.shuffle.transport.type": "TCP",
            "spark.rapids.shuffle.tcp.driverEndpoint": srv.endpoint,
        })
        ma = ShuffleManager(conf, executor_id="exec-a")
        mb = ShuffleManager(conf, executor_id="exec-b")
        try:
            t = pa.table({"x": list(range(100)),
                          "s": [f"v{i}" for i in range(100)]})
            batch = arrow_to_device(t)
            ma.write_map_output(7, 0, [batch])
            mb.heartbeat = mb.heartbeats  # ensure peers fresh
            got = mb.read_reduce_partition(7, num_maps=1, reduce_id=0)
            assert got is not None
            from spark_rapids_tpu.columnar.convert import device_to_arrow
            out = device_to_arrow(got)
            assert out["x"].to_pylist() == list(range(100))
            assert out["s"].to_pylist() == [f"v{i}" for i in range(100)]
        finally:
            ma.close()
            mb.close()
    finally:
        srv.close()


_CHILD_SCRIPT = r"""
import sys, time
from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
from spark_rapids_tpu.shuffle.transport import BlockId
t = TcpShuffleTransport("child-exec")
t.publish("child-exec", BlockId(9, 2, 5), b"frame-from-child-process")
print("ENDPOINT", t.endpoint, flush=True)
time.sleep(30)
"""


def test_two_process_block_fetch(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", _CHILD_SCRIPT],
                            stdout=subprocess.PIPE, env=env, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("ENDPOINT "), line
        endpoint = line.split()[1]
        me = TcpShuffleTransport("parent-exec")
        try:
            peer = PeerInfo("child-exec", endpoint)
            got = me.fetch(peer, BlockId(9, 2, 5))
            assert got == b"frame-from-child-process"
            assert me.fetch(peer, BlockId(9, 2, 6)) is None
        finally:
            me.close()
    finally:
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------
# native (C++) transport — same SPI, same wire protocol
# ---------------------------------------------------------------------------

def _native_available():
    from spark_rapids_tpu.shuffle import native_tcp
    return native_tcp.available()


@pytest.mark.skipif(not _native_available(),
                    reason="native transport library unavailable")
class TestNativeTransport:
    def test_native_fetch_round_trip(self):
        from spark_rapids_tpu.shuffle.native_tcp import \
            NativeTcpShuffleTransport
        a = NativeTcpShuffleTransport("exec-a")
        b = NativeTcpShuffleTransport("exec-b")
        try:
            blk = BlockId(1, 0, 3)
            a.publish("exec-a", blk, b"native-frame")
            peer_a = PeerInfo("exec-a", a.endpoint)
            assert b.fetch(peer_a, blk) == b"native-frame"
            assert b.fetch(peer_a, BlockId(1, 0, 4)) is None
            # local short-circuit
            b.publish("exec-b", BlockId(2, 1, 1), b"mine")
            assert b.fetch(PeerInfo("exec-b", b.endpoint),
                           BlockId(2, 1, 1)) == b"mine"
            # connection reuse + large frames
            big = bytes(range(256)) * 4096  # 1 MiB
            for i in range(8):
                a.publish("exec-a", BlockId(3, i, 0), big)
            for i in range(8):
                assert b.fetch(peer_a, BlockId(3, i, 0)) == big
            # blocks_of / clear bookkeeping
            assert len(a.blocks_of("exec-a")) == 9
            a.clear(3)
            assert len(a.blocks_of("exec-a")) == 1
            a.clear()
            assert a.blocks_of("exec-a") == []
        finally:
            a.close()
            b.close()

    def test_native_and_python_interop(self):
        """The wire protocol is shared: a Python client fetches from the
        native server and vice versa (mixed deployments)."""
        from spark_rapids_tpu.shuffle.native_tcp import \
            NativeTcpShuffleTransport
        native = NativeTcpShuffleTransport("exec-n")
        py = TcpShuffleTransport("exec-p")
        try:
            native.publish("exec-n", BlockId(7, 1, 2), b"from-native")
            py.publish("exec-p", BlockId(7, 2, 1), b"from-python")
            assert py.fetch(PeerInfo("exec-n", native.endpoint),
                            BlockId(7, 1, 2)) == b"from-native"
            assert native.fetch(PeerInfo("exec-p", py.endpoint),
                                BlockId(7, 2, 1)) == b"from-python"
            assert py.fetch(PeerInfo("exec-n", native.endpoint),
                            BlockId(7, 9, 9)) is None
            assert native.fetch(PeerInfo("exec-p", py.endpoint),
                                BlockId(7, 9, 9)) is None
        finally:
            native.close()
            py.close()

    def test_native_fetch_failure_raises(self):
        from spark_rapids_tpu.shuffle.native_tcp import \
            NativeTcpShuffleTransport
        from spark_rapids_tpu.shuffle.tcp import ShuffleFetchFailed
        t = NativeTcpShuffleTransport("exec-x")
        try:
            with pytest.raises(ShuffleFetchFailed):
                t.fetch(PeerInfo("gone", "127.0.0.1:9"), BlockId(1, 1, 1))
        finally:
            t.close()

    def test_manager_selects_native_when_enabled(self):
        from spark_rapids_tpu.config import RapidsConf
        from spark_rapids_tpu.shuffle.manager import _transport_from_conf
        from spark_rapids_tpu.shuffle.native_tcp import \
            NativeTcpShuffleTransport
        conf = RapidsConf.get_global().copy(
            {"spark.rapids.shuffle.transport.type": "TCP"})
        tr, hb = _transport_from_conf(conf, "exec-sel")
        try:
            assert isinstance(tr, NativeTcpShuffleTransport)
        finally:
            tr.close()
        conf = conf.copy(
            {"spark.rapids.shuffle.tcp.native.enabled": False})
        tr, hb = _transport_from_conf(conf, "exec-sel2")
        try:
            assert isinstance(tr, TcpShuffleTransport)
        finally:
            tr.close()
