"""t-digest approx_percentile — kernel accuracy, strategy selection, and
the digest-per-batch merge path that keeps percentile memory bounded at
O(groups x delta/2) regardless of group size (reference
``GpuApproximatePercentile.scala:1-222``; VERDICT r2 #7)."""

import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


def _rank_err(sorted_vals, est, p):
    return abs(np.searchsorted(sorted_vals, est) / len(sorted_vals) - p)


@pytest.fixture(autouse=True)
def _restore_conf():
    """session(**conf) mutates the process-global conf — restore the keys
    these tests touch so later modules see the defaults."""
    yield
    srt.session(**{
        "spark.rapids.sql.approxPercentile.strategy": "auto",
        "spark.rapids.sql.reader.chunked": True,
        "spark.rapids.sql.reader.chunked.targetRows": 1 << 21})


class TestKernel:
    @pytest.mark.parametrize("G,per", [(50, 300), (64, 2000), (200, 37)])
    def test_accuracy_vs_oracle(self, G, per):
        from spark_rapids_tpu.ops import tdigest as TD
        rng = np.random.default_rng(0)
        vals = rng.normal(100, 20, G * per)
        grp = np.repeat(np.arange(G), per)
        ones = np.ones(G * per)
        means, wts, vmin, vmax, total = TD.build_grouped(
            np, vals, ones, ones.astype(bool), grp, ones.astype(bool),
            G, 100)
        outs = TD.percentiles_grouped(np, means, wts, vmin, vmax, total,
                                      [0.01, 0.5, 0.99])
        worst = 0.0
        for gi in range(G):
            gv = np.sort(vals[grp == gi])
            for pi, p in enumerate([0.01, 0.5, 0.99]):
                worst = max(worst, _rank_err(gv, outs[pi][gi], p))
        assert worst < 0.03 + 1.0 / per

    def test_jnp_matches_numpy(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import tdigest as TD
        rng = np.random.default_rng(1)
        n, G = 30_000, 32
        vals, grp = rng.random(n) * 100, rng.integers(0, G, n)
        ones = np.ones(n)
        a = TD.build_grouped(np, vals, ones, ones.astype(bool), grp,
                             ones.astype(bool), G, 100)
        b = TD.build_grouped(jnp, jnp.asarray(vals), jnp.asarray(ones),
                             jnp.asarray(ones.astype(bool)),
                             jnp.asarray(grp),
                             jnp.asarray(ones.astype(bool)), G, 100)
        pa_ = TD.percentiles_grouped(np, *a, [0.5])[0]
        pb = np.asarray(TD.percentiles_grouped(jnp, *b, [0.5])[0])
        assert np.allclose(pa_, pb, rtol=1e-9)

    def test_weighted_merge_matches_single_pass(self):
        from spark_rapids_tpu.ops import tdigest as TD
        rng = np.random.default_rng(2)
        n, G, delta = 80_000, 16, 100
        vals, grp = rng.normal(0, 1, n), rng.integers(0, G, n)
        C = TD.n_centroids(delta)
        ev, ew, eg, los, his = [], [], [], [], []
        for ch in np.array_split(np.arange(n), 3):
            ones = np.ones(len(ch))
            m, w, lo, hi, _t = TD.build_grouped(
                np, vals[ch], ones, ones.astype(bool), grp[ch],
                ones.astype(bool), G, delta)
            gg = np.repeat(np.arange(G), C)
            sel = w.ravel() > 0
            ev.append(m.ravel()[sel]); ew.append(w.ravel()[sel])
            eg.append(gg[sel]); los.append(lo); his.append(hi)
        ev, ew, eg = map(np.concatenate, (ev, ew, eg))
        ones = np.ones(len(ev), bool)
        m, w, _lo, _hi, total = TD.build_grouped(np, ev, ew, ones, eg,
                                                 ones, G, delta)
        vmin = np.min(np.stack(los), axis=0)
        vmax = np.max(np.stack(his), axis=0)
        est = TD.percentiles_grouped(np, m, w, vmin, vmax, total, [0.5])[0]
        worst = 0.0
        for gi in range(G):
            gv = np.sort(vals[grp == gi])
            worst = max(worst, _rank_err(gv, est[gi], 0.5))
        assert worst < 0.01
        assert np.allclose(total, np.bincount(grp, minlength=G))


class TestEngine:
    def test_tdigest_strategy_grouped(self):
        rng = np.random.default_rng(3)
        n, G = 300_000, 500
        t = pa.table({"k": rng.integers(0, G, n),
                      "v": rng.normal(100, 20, n)})
        sess = srt.session(**{
            "spark.rapids.sql.approxPercentile.strategy": "tdigest"})
        df = sess.create_dataframe(t, num_partitions=4)
        got = (df.groupBy("k")
               .agg(F.percentile_approx(df.v, [0.1, 0.9]).alias("pq"),
                    F.percentile_approx(df.v, 0.5).alias("p50"))
               .collect().to_pandas())
        assert len(got) == G
        pdf = t.to_pandas()
        for gi in rng.choice(G, 20, replace=False):
            gv = np.sort(pdf[pdf.k == gi].v.values)
            row = got[got.k == gi].iloc[0]
            assert _rank_err(gv, row["p50"], 0.5) < 0.03
            for est, p in zip(row["pq"], [0.1, 0.9]):
                assert _rank_err(gv, est, p) < 0.03

    def test_exact_strategy_unchanged(self):
        """strategy=exact keeps the ordinal rule bit-for-bit."""
        t = pa.table({"k": [1, 1, 1, 1, 2, 2], "v": [1., 2., 3., 4., 7., 9.]})
        sess = srt.session(**{
            "spark.rapids.sql.approxPercentile.strategy": "exact"})
        df = sess.create_dataframe(t)
        got = (df.groupBy("k").agg(F.percentile_approx(df.v, 0.5).alias("p"))
               .collect().to_pandas().sort_values("k"))
        assert list(got["p"]) == [2.0, 7.0]

    def test_integral_input_returns_integral(self):
        rng = np.random.default_rng(4)
        t = pa.table({"k": rng.integers(0, 10, 50_000),
                      "v": rng.integers(0, 1000, 50_000).astype(np.int64)})
        sess = srt.session(**{
            "spark.rapids.sql.approxPercentile.strategy": "tdigest"})
        df = sess.create_dataframe(t)
        got = (df.groupBy("k").agg(F.percentile_approx(df.v, 0.5).alias("p"))
               .collect())
        assert got.schema.field("p").type in (pa.int64(),)

    def test_chunked_scan_merges_digests(self):
        """Chunked parquet scan: each chunk digests separately; the merge
        path must engage (no raw-row concat) and stay accurate."""
        rng = np.random.default_rng(5)
        n, G = 300_000, 30
        t = pa.table({"k": rng.integers(0, G, n).astype(np.int64),
                      "v": rng.normal(0, 1, n)})
        d = tempfile.mkdtemp()
        path = os.path.join(d, "t.parquet")
        pq.write_table(t, path, row_group_size=30_000)
        sess = srt.session(**{
            "spark.rapids.sql.approxPercentile.strategy": "tdigest",
            "spark.rapids.sql.reader.chunked": True,
            "spark.rapids.sql.reader.chunked.targetRows": 40_000})
        got = (sess.read.parquet(path).groupBy("k")
               .agg(F.percentile_approx(F.col("v"), 0.5).alias("p"))
               .collect().to_pandas())
        m = sess.last_query_metrics
        assert m.get("aggTdigestMergedBatches", 0) > 1, m
        assert len(got) == G
        pdf = t.to_pandas()
        for gi in range(G):
            gv = np.sort(pdf[pdf.k == gi].v.values)
            assert _rank_err(gv, got[got.k == gi].p.iloc[0], 0.5) < 0.02

    def test_auto_uses_exact_for_small(self):
        """auto keeps small batches on the exact ordinal rule."""
        t = pa.table({"k": [1] * 5, "v": [5., 1., 3., 2., 4.]})
        sess = srt.session()
        df = sess.create_dataframe(t)
        got = (df.groupBy("k").agg(F.percentile_approx(df.v, 0.5).alias("p"))
               .collect().to_pylist())
        assert got[0]["p"] == 3.0

    def test_all_null_group_emits_null_row(self):
        """A group whose percentile input is entirely NULL must still
        appear in the output with a NULL percentile — including on the
        multi-batch digest-merge path (anchor rows)."""
        rng = np.random.default_rng(6)
        n, G = 120_000, 20
        ks = rng.integers(0, G, n).astype(np.int64)
        vs = rng.normal(0, 1, n)
        null_mask = ks == 7          # group 7: all values NULL
        t = pa.table({"k": ks,
                      "v": pa.array(np.where(null_mask, np.nan, vs),
                                    mask=null_mask)})
        d = tempfile.mkdtemp()
        path = os.path.join(d, "t.parquet")
        pq.write_table(t, path, row_group_size=20_000)
        sess = srt.session(**{
            "spark.rapids.sql.approxPercentile.strategy": "tdigest",
            "spark.rapids.sql.reader.chunked": True,
            "spark.rapids.sql.reader.chunked.targetRows": 25_000})
        got = (sess.read.parquet(path).groupBy("k")
               .agg(F.percentile_approx(F.col("v"), 0.5).alias("p"))
               .collect().to_pandas())
        m = sess.last_query_metrics
        assert m.get("aggTdigestMergedBatches", 0) > 1, m
        assert len(got) == G, f"missing groups: {sorted(set(range(G)) - set(got.k))}"
        assert got[got.k == 7].p.isna().all()
        assert got[got.k != 7].p.notna().all()
