"""Live telemetry plane (observability/server.py + slo.py) and
cross-process trace stitching over the shuffle wire (shuffle/tcp.py
traced fetch op + serializer frame-trace extension + tools/trace_merge).
"""

import json
import os
import socket
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.observability import slo as OSLO
from spark_rapids_tpu.observability import tracer as OT
from spark_rapids_tpu.observability.metrics import MetricsRegistry
from spark_rapids_tpu.observability.server import TelemetryServer

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import check_trace  # noqa: E402
import trace_merge  # noqa: E402


def _get(base: str, route: str):
    try:
        with urllib.request.urlopen(base + route, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# telemetry server
# ---------------------------------------------------------------------------

def test_server_routes_payloads_and_503():
    healthy = [True]
    srv = TelemetryServer(
        metrics_text=lambda: "# TYPE srt_x counter\nsrt_x 1.0\n",
        healthz=lambda: (healthy[0],
                         {"status": "ok" if healthy[0] else "degraded"}),
        queries=lambda: [{"query": 1, "status": "ok"}],
        doctor=lambda: {"last": None},
        slo=lambda: {"schema": "srt-slo/1", "tenants": {}})
    try:
        base = srv.endpoint
        st, body = _get(base, "/metrics")
        assert st == 200 and "srt_x 1.0" in body
        st, body = _get(base, "/healthz")
        assert st == 200 and json.loads(body)["status"] == "ok"
        st, body = _get(base, "/queries")
        assert st == 200 and json.loads(body)[0]["query"] == 1
        for route in ("/doctor", "/slo"):
            st, body = _get(base, route)
            assert st == 200
            json.loads(body)
        # degraded flips /healthz non-200 while /metrics keeps serving
        healthy[0] = False
        st, body = _get(base, "/healthz")
        assert st == 503 and json.loads(body)["status"] == "degraded"
        assert _get(base, "/metrics")[0] == 200
        # unknown route: 404 naming the known ones
        st, body = _get(base, "/nope")
        assert st == 404 and "/metrics" in body
    finally:
        srv.close()


def test_server_shutdown_is_leak_free():
    srv = TelemetryServer(
        metrics_text=lambda: "", healthz=lambda: (True, {}),
        queries=lambda: [], doctor=lambda: {}, slo=lambda: {})
    host, port = srv.host, srv.port
    assert _get(srv.endpoint, "/healthz")[0] == 200
    srv.close()
    srv.close()  # idempotent
    assert not [t for t in threading.enumerate()
                if t.name == f"srt-telemetry-{port}"]
    probe = socket.socket()
    probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    probe.bind((host, port))
    probe.close()


def test_server_source_exception_is_500_not_fatal():
    def boom():
        raise RuntimeError("source failed")
    srv = TelemetryServer(
        metrics_text=lambda: "", healthz=lambda: (True, {}),
        queries=boom, doctor=lambda: {}, slo=lambda: {})
    try:
        st, body = _get(srv.endpoint, "/queries")
        assert st == 500 and "source failed" in body
        # the serve thread survives the exception
        assert _get(srv.endpoint, "/healthz")[0] == 200
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------

def _slo_conf(**extra):
    base = {"spark.rapids.tpu.slo.latencyObjectiveMs": 10.0,
            "spark.rapids.tpu.slo.latencyTarget": 0.99,
            "spark.rapids.tpu.slo.availabilityTarget": 0.999,
            "spark.rapids.tpu.slo.burnWindowsS": "300,3600"}
    base.update(extra)
    return RapidsConf.get_global().copy(base)


def _feed(reg, tenant, n_ok, lat_ms, n_err=0):
    for _ in range(n_ok):
        reg.observe("query_ms", lat_ms, status="ok", tenant=tenant)
        reg.inc("queries_total", status="ok", tenant=tenant)
    for _ in range(n_err):
        reg.inc("queries_total", status="error", tenant=tenant)


def test_slo_burn_rates_and_admission_hint():
    now = [1000.0]
    tracker = OSLO.SloTracker(OSLO.SloObjectives.from_conf(_slo_conf()),
                              clock=lambda: now[0])
    reg = MetricsRegistry()
    _feed(reg, "A", n_ok=50, lat_ms=100.0, n_err=5)  # slow AND erroring
    _feed(reg, "B", n_ok=50, lat_ms=1.0)             # healthy
    now[0] = 1100.0
    rep = tracker.report(registry=reg)
    assert rep["schema"] == "srt-slo/1"
    a, b = rep["tenants"]["A"], rep["tenants"]["B"]
    assert a["burning"] and not b["burning"]
    w = a["windows"]["300s"]
    assert w["error_burn"] > 1.0 and w["latency_burn"] > 1.0
    assert b["windows"]["300s"]["error_burn"] == 0.0
    assert tracker.admission_hint("A")["burning"]
    assert not tracker.admission_hint("B")["burning"]
    assert not tracker.admission_hint("unseen")["burning"]


def test_slo_burn_is_windowed_not_cumulative():
    """Old badness outside every window must stop burning: the tracker
    reports deltas over its windows, not lifetime totals."""
    now = [1000.0]
    tracker = OSLO.SloTracker(OSLO.SloObjectives.from_conf(_slo_conf()),
                              clock=lambda: now[0])
    reg = MetricsRegistry()
    _feed(reg, "A", n_ok=10, lat_ms=100.0)
    now[0] = 1100.0
    assert tracker.report(registry=reg)["tenants"]["A"]["burning"]
    # 2h of healthy traffic later the slow burst left every window
    for t in range(72):
        now[0] += 100.0
        _feed(reg, "A", n_ok=5, lat_ms=1.0)
        rep = tracker.report(registry=reg)
    assert not rep["tenants"]["A"]["burning"], rep["tenants"]["A"]


def test_slo_doctor_verdict_passes_schema_check(tmp_path):
    now = [1000.0]
    tracker = OSLO.SloTracker(OSLO.SloObjectives.from_conf(_slo_conf()),
                              clock=lambda: now[0])
    reg = MetricsRegistry()
    _feed(reg, "A", n_ok=50, lat_ms=100.0, n_err=5)
    now[0] = 1100.0
    v = tracker.doctor_verdict(registry=reg)
    assert v["verdict"] == "slo-burn"
    assert v["ranked"][0]["tenant"] == "A"
    assert "A" in v["ranked"][0]["evidence"]
    p = tmp_path / "slo_doctor.json"
    p.write_text(json.dumps(v))
    assert check_trace.check_doctor(str(p)) == ("slo-burn", 1)
    # quiet fleet: no-bottleneck, empty ranking
    quiet = OSLO.SloTracker(OSLO.SloObjectives.from_conf(_slo_conf()),
                            clock=lambda: now[0])
    assert quiet.doctor_verdict(
        registry=MetricsRegistry())["verdict"] == "no-bottleneck"


# ---------------------------------------------------------------------------
# trace context + ring health gauges
# ---------------------------------------------------------------------------

def test_trace_context_gating_and_span_ids():
    assert not OT.TRACING["on"]
    assert OT.current_trace_context() is None  # off -> no context, ever
    ids = {OT.next_span_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(i.startswith(f"{os.getpid():x}.") for i in ids)
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = True
    try:
        ctx = OT.current_trace_context()
        assert ctx is not None and ctx["trace"]
    finally:
        OT.TRACING["on"] = prev


def test_fetch_trace_is_thread_local():
    OT.set_fetch_trace({"trace": "t1", "span": "s1"})
    seen = []
    th = threading.Thread(target=lambda: seen.append(OT.fetch_trace()))
    th.start()
    th.join()
    assert seen == [None]
    assert OT.fetch_trace() == {"trace": "t1", "span": "s1"}
    OT.set_fetch_trace(None)


def test_ring_health_metrics_feed():
    from spark_rapids_tpu.observability import metrics as OM
    tracer = OT.get_tracer()
    prev_t, prev_m = OT.TRACING["on"], OM.METRICS["on"]
    reg = OM.get_registry()
    tracer.reset(capacity=16)  # ring capacity floors at 16
    OT.TRACING["on"] = OM.METRICS["on"] = True
    try:
        for i in range(40):  # capacity 16 -> 24 dropped
            tracer.complete("op", f"ev{i}", 0.0, 0.001)
        snap = reg.json_snapshot()
        gauges = {g["name"]: g["value"] for g in snap["gauges"]}
        counters = {c["name"]: c["value"] for c in snap["counters"]}
        assert gauges.get("trace_ring_high_water", 0) >= 16
        assert counters.get("trace_dropped_events_total", 0) >= 24
        text = reg.prometheus_text()
        assert "srt_trace_ring_high_water" in text
        assert "srt_trace_dropped_events_total" in text
    finally:
        OT.TRACING["on"], OM.METRICS["on"] = prev_t, prev_m
        tracer.reset()


# ---------------------------------------------------------------------------
# traced shuffle wire + stitching
# ---------------------------------------------------------------------------

def test_tcp_traced_fetch_emits_linked_serve_span():
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId, PeerInfo
    tracer = OT.get_tracer()
    tracer.reset(session="stitch-test")
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = True
    a = TcpShuffleTransport("exec-a")
    b = TcpShuffleTransport("exec-b")
    try:
        blk = BlockId(5, 0, 1)
        a.publish("exec-a", blk, b"traced-frame-bytes")
        ctx = {"trace": "sess-1:q7", "span": "abc.1", "tenant": "t0"}
        OT.set_fetch_trace(ctx)
        try:
            got = b.fetch(PeerInfo("exec-a", a.endpoint), blk)
        finally:
            OT.set_fetch_trace(None)
        assert got == b"traced-frame-bytes"
        serves = [e for e in tracer.snapshot()
                  if e["name"] == "shuffle.serve"]
        assert serves, "no serve span emitted by the traced op"
        args = serves[-1]["args"]
        assert args["trace_id"] == "sess-1:q7"
        assert args["parent_span"] == "abc.1"
        assert args["requester"] == "exec-b"
        assert args["span_id"]
        # untraced fetch still works and emits no new serve span
        n = len(serves)
        assert b.fetch(PeerInfo("exec-a", a.endpoint), blk) == got
        assert len([e for e in tracer.snapshot()
                    if e["name"] == "shuffle.serve"]) == n
    finally:
        OT.TRACING["on"] = prev
        a.close()
        b.close()
        tracer.reset()


def test_tcp_traced_fetch_falls_back_on_old_peer():
    """A peer that answers the traced op with an error (an old binary)
    must be remembered and served via the plain op — same bytes."""
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId, PeerInfo
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = True
    a = TcpShuffleTransport("exec-a")
    b = TcpShuffleTransport("exec-b")
    # simulate an old peer: its server rejects op 4 like an unknown op
    a._handle_traced = lambda js: {"error": "unknown op 4"}
    try:
        blk = BlockId(6, 0, 0)
        a.publish("exec-a", blk, b"old-peer-frame")
        OT.set_fetch_trace({"trace": "t", "span": "s", "tenant": ""})
        try:
            got = b.fetch(PeerInfo("exec-a", a.endpoint), blk)
        finally:
            OT.set_fetch_trace(None)
        assert got == b"old-peer-frame"
        assert b._no_trace.get(a.endpoint), \
            "old peer not remembered in _no_trace"
        # second fetch goes straight to the plain op
        assert b.fetch(PeerInfo("exec-a", a.endpoint), blk) == got
    finally:
        OT.TRACING["on"] = prev
        a.close()
        b.close()


def test_serializer_frame_trace_extension_and_compat():
    from spark_rapids_tpu.columnar.convert import (arrow_to_device,
                                                   device_to_arrow)
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    t = pa.table({"x": np.arange(64, dtype=np.int64),
                  "y": np.random.default_rng(0).random(64)})
    batch = arrow_to_device(t)
    tracer = OT.get_tracer()
    assert not OT.TRACING["on"]
    frame_off = serialize_batch(batch)
    assert b'"trace"' not in frame_off  # off: wire bytes unchanged
    OT.TRACING["on"] = True
    tracer.reset(session="ser-test")
    try:
        frame_on = serialize_batch(batch)
        assert b'"trace"' in frame_on  # on: versioned schema extension
        # new reader surfaces the producer's context on its span
        out = deserialize_batch(frame_on)
        assert device_to_arrow(out).equals(t)
        des = [e for e in tracer.snapshot()
               if e["name"] == "deserialize_batch"][-1]
        assert des["args"]["producer_trace"]
        assert des["args"]["producer_span"]
    finally:
        OT.TRACING["on"] = False
        tracer.reset()
    # old reader (tracing off) ignores the extension: same rows
    out = deserialize_batch(frame_on)
    assert device_to_arrow(out).equals(t)
    # and results are bit-identical across traced/untraced frames
    assert device_to_arrow(deserialize_batch(frame_off)).equals(t)


def test_local_transport_parity_serve_span():
    """Single-process stitching parity: LocalTransport emits the same
    shuffle.serve span the TCP server does, so merge/flow validation is
    testable without sockets."""
    from spark_rapids_tpu.shuffle.transport import (BlockId, LocalTransport,
                                                    PeerInfo)
    tracer = OT.get_tracer()
    tracer.reset(session="local-par")
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = True
    tr = LocalTransport()
    try:
        blk = BlockId(4, 1, 1)
        tr.publish("exec-l", blk, b"local-frame")
        OT.set_fetch_trace({"trace": "t9", "span": "p.1", "tenant": ""})
        try:
            assert tr.fetch(PeerInfo("exec-l", ""), blk) == b"local-frame"
        finally:
            OT.set_fetch_trace(None)
        serve = [e for e in tracer.snapshot()
                 if e["name"] == "shuffle.serve"][-1]
        assert serve["args"]["parent_span"] == "p.1"
        assert serve["args"]["trace_id"] == "t9"
    finally:
        OT.TRACING["on"] = prev
        tr.close()
        tracer.reset()


def test_trace_merge_stitches_flow_events(tmp_path):
    """Two synthetic per-process logs -> one merged trace whose flow
    events pass check_trace --flow (each endpoint inside a span, shared
    id, processes named)."""
    from spark_rapids_tpu.observability.export import write_event_log

    requester = [{"ph": "X", "name": "shuffle.fetch.remote",
                  "cat": "shuffle", "ts": 1000.0, "dur": 500.0,
                  "tid": 1, "args": {"span_id": "aa.1",
                                     "trace_id": "s:q1"}}]
    peer = [{"ph": "X", "name": "shuffle.serve", "cat": "shuffle",
             "ts": 50.0, "dur": 80.0, "tid": 7,
             "args": {"span_id": "bb.1", "parent_span": "aa.1",
                      "trace_id": "s:q1"}}]
    lg1 = tmp_path / "p1.jsonl"
    lg2 = tmp_path / "p2.jsonl"
    write_event_log(str(lg1), requester,
                    {"epoch_unix_s": 100.0, "pid": 111, "session_id": "a"})
    # peer epoch 1ms later: merge must normalize onto one clock
    write_event_log(str(lg2), peer,
                    {"epoch_unix_s": 100.001, "pid": 222,
                     "session_id": "b"})
    doc = trace_merge.merge([str(lg1), str(lg2)])
    assert doc["otherData"]["flows"] == 1
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert len({e["id"] for e in flows}) == 1
    s = next(e for e in flows if e["ph"] == "s")
    f = next(e for e in flows if e["ph"] == "f")
    assert s["pid"] != f["pid"]
    # peer ts shifted by the 1ms epoch delta onto the global clock
    assert f["ts"] == pytest.approx(50.0 + 1000.0)
    out = tmp_path / "merged.json"
    out.write_text(json.dumps(doc))
    n, cross, pids = check_trace.check_flow(str(out))
    assert (n, cross, pids) == (1, 1, 2)
    # CLI path too
    assert trace_merge.main([str(tmp_path / "m2.json"),
                             str(lg1), str(lg2)]) == 0
    assert check_trace.main(["--flow", str(tmp_path / "m2.json")]) == 0


def test_check_trace_endpoint_scrape_mode():
    srv = TelemetryServer(
        metrics_text=lambda: ("# TYPE srt_q_total counter\n"
                              'srt_q_total{tenant="t0"} 3.0\n'),
        healthz=lambda: (True, {}), queries=lambda: [],
        doctor=lambda: {}, slo=lambda: {})
    try:
        url = srv.endpoint + "/metrics"
        assert check_trace.check_endpoint(url) == "1 samples, 1 families"
        assert check_trace.main(
            ["--endpoint", url, "--prometheus-label", "tenant"]) == 0
        with pytest.raises(ValueError):
            check_trace.check_endpoint(url, require_label="absent")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# session/engine integration (conf-gated, off by default)
# ---------------------------------------------------------------------------

def test_session_telemetry_off_by_default_and_gated_start():
    import spark_rapids_tpu as srt
    sess = srt.session()
    assert sess.telemetry is None
    sess2 = srt.session(**{"spark.rapids.tpu.telemetry.enabled": True,
                           "spark.rapids.tpu.telemetry.port": 0})
    try:
        assert sess2.telemetry is not None
        st, body = _get(sess2.telemetry.endpoint, "/healthz")
        assert st == 200 and json.loads(body)["status"] == "ok"
        assert _get(sess2.telemetry.endpoint, "/doctor")[0] == 200
    finally:
        port = sess2.telemetry.port
        sess2.close_telemetry()
        assert sess2.telemetry is None
        sess2.close_telemetry()  # idempotent
        assert not [t for t in threading.enumerate()
                    if t.name == f"srt-telemetry-{port}"]
