"""Query-timeline tracer (observability/): ring-buffer semantics,
thread safety, Chrome-trace/JSONL export schema, session wiring
(profile_last_query attribution, export_chrome_trace, kernel-cache
deltas in last_query_metrics), flag restore-on-exception, and the
nested-TaskContext regression (PR 3 satellites)."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.observability import export as OE
from spark_rapids_tpu.observability import report as OR
from spark_rapids_tpu.observability import tracer as OT
from spark_rapids_tpu.sql import functions as F

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracing_on():
    """Fresh tracer + flag on, restored afterwards."""
    prev = OT.TRACING["on"]
    OT.get_tracer().reset(256)
    OT.TRACING["on"] = True
    yield OT.get_tracer()
    OT.TRACING["on"] = prev
    OT.get_tracer().reset()


# --------------------------------------------------------------------------
# ring buffer + thread safety
# --------------------------------------------------------------------------

def test_disabled_span_is_null_object():
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = False
    try:
        OT.get_tracer().reset()
        with OT.span("sync", "x", bytes=1):
            pass
        assert OT.get_tracer().snapshot() == []
    finally:
        OT.TRACING["on"] = prev


def test_ring_overflow_keeps_newest_and_counts_drops(tracing_on):
    tr = tracing_on
    tr.reset(capacity=16)
    for i in range(40):
        with OT.span("op", f"e{i}"):
            pass
    events = tr.snapshot()
    assert len(events) == 16
    # newest events kept (the last 16 emitted)
    assert [e["name"] for e in events] == [f"e{i}" for i in range(24, 40)]
    assert tr.dropped_events == 24


def test_thread_safety_under_pool(tracing_on):
    """Concurrent emitters (the shuffle writer/reader pool shape) must
    neither crash nor lose accounting: events kept + dropped == emitted."""
    tr = tracing_on
    tr.reset(capacity=64)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def emit(t):
        barrier.wait()
        for i in range(per_thread):
            tr.complete("shuffle", f"t{t}-{i}", 0.0, 0.001, bytes=i)

    threads = [threading.Thread(target=emit, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = tr.snapshot()
    assert len(events) == 64
    assert len(events) + tr.dropped_events == n_threads * per_thread


def test_exec_stack_nests_and_attributes(tracing_on):
    tr = tracing_on
    assert OT.current_exec() == ""
    OT.push_exec("Outer")
    OT.push_exec("Inner")
    tr.complete("sync", "readback", 0.0, 0.002)
    OT.pop_exec()
    tr.complete("sync", "readback", 0.0, 0.003)
    OT.pop_exec()
    assert OT.current_exec() == ""
    evs = tr.snapshot()
    assert evs[0]["exec"] == "Inner" and evs[1]["exec"] == "Outer"
    agg = OR.aggregate_by_exec(evs)
    assert agg["Inner"]["sync_n"] == 1 and agg["Outer"]["sync_n"] == 1


# --------------------------------------------------------------------------
# export schema
# --------------------------------------------------------------------------

def _check_chrome_schema(doc):
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        for field in ("ph", "ts", "pid", "tid", "name"):
            assert field in ev, (field, ev)
        assert ev["ph"] in ("X", "C", "i", "M", "B", "E")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0


def test_chrome_trace_schema(tracing_on, tmp_path):
    tr = tracing_on
    with OT.span("d2h", "fetch", bytes=128):
        pass
    tr.counter("readbacks", 2)
    path = str(tmp_path / "trace.json")
    OE.write_chrome_trace(path, tr.snapshot(), tr.meta())
    with open(path) as fh:
        doc = json.load(fh)
    _check_chrome_schema(doc)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert spans[0]["name"] == "fetch" and spans[0]["cat"] == "d2h"
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert counters and counters[0]["args"]["value"] == 2


def test_check_trace_tool(tracing_on, tmp_path):
    """tools/check_trace.py (the CI validator) accepts a real export and
    rejects a broken one."""
    tr = tracing_on
    with OT.span("sync", "s"):
        pass
    good = str(tmp_path / "good.json")
    OE.write_chrome_trace(good, tr.snapshot(), tr.meta())
    tool = os.path.join(REPO, "tools", "check_trace.py")
    assert subprocess.run([sys.executable, tool, good]).returncode == 0
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as fh:
        json.dump({"traceEvents": [{"ph": "X", "ts": 0}]}, fh)
    assert subprocess.run([sys.executable, tool, bad]).returncode != 0


def test_jsonl_event_log_round_trip(tracing_on, tmp_path):
    tr = tracing_on
    with OT.span("spill", "spill.deviceToHost", bytes=64):
        pass
    with OT.span("h2d", "upload", bytes=32):
        pass
    path = str(tmp_path / "log.jsonl")
    meta = dict(tr.meta(), query=1)
    OE.write_event_log(path, tr.snapshot(), meta)
    # append-only: a second query's log stacks in the same file
    OE.write_event_log(path, tr.snapshot(), dict(meta, query=2))
    logs = OE.read_event_log(path)
    assert len(logs) == 2
    for got_meta, got_events in logs:
        assert got_events == tr.snapshot()
    assert logs[0][0]["query"] == 1 and logs[1][0]["query"] == 2


# --------------------------------------------------------------------------
# session wiring (end-to-end on the join micro-shape)
# --------------------------------------------------------------------------

def _join_query(sess, n=20000, salt=0):
    rng = np.random.default_rng(7)
    fact = pa.table({"fk": rng.integers(0, 500, n), "x": rng.random(n)})
    dim = pa.table({"pk": np.arange(500, dtype=np.int64),
                    "cat": rng.integers(0, 8, 500)})
    f = sess.create_dataframe(fact, num_partitions=2)
    d = sess.create_dataframe(dim)
    return (f.join(d, f.fk == d.pk, "inner")
            .filter(F.col("x") >= float(salt))  # salt -> fresh kernel keys
            .groupBy("cat")
            .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
            .orderBy("cat"))


def test_traced_join_attribution_and_export(tmp_path):
    sess = srt.session(**{"spark.rapids.tpu.profile.enabled": True})
    _join_query(sess).collect()
    report = sess.profile_last_query()
    # per-exec columns for self-time, sync, compile, h2d/d2h bytes
    for col in ("self_ms", "sync_ms", "compile_ms", "h2d", "d2h"):
        assert col in report, report
    assert "Join" in report
    summary = sess.last_query_trace_summary
    assert summary["sync_count"] >= 1          # join sizing readback
    assert summary["h2d_bytes"] > 0            # arrow -> device upload
    assert summary["d2h_bytes"] > 0            # result fetch
    path = str(tmp_path / "join_trace.json")
    assert sess.export_chrome_trace(path) == path
    with open(path) as fh:
        doc = json.load(fh)
    _check_chrome_schema(doc)
    cats = {e.get("cat") for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "op" in cats and ("sync" in cats or "d2h" in cats)
    # a join sizing readback attributed to a join exec node
    syncs = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e.get("cat") == "sync"]
    assert any("Join" in e["args"].get("exec", "") for e in syncs), syncs


def test_kernel_cache_stats_in_last_query_metrics():
    sess = srt.session(**{"spark.rapids.tpu.trace.sink": "memory"})
    q = _join_query(sess, salt=1)  # distinct literal -> fresh kernels
    q.collect()
    cold = dict(sess.last_query_metrics)
    assert cold["kernelCacheMisses"] > 0
    assert cold["kernelCompiles"] > 0
    assert cold["kernelCompileMs"] > 0
    q.collect()
    warm = dict(sess.last_query_metrics)
    assert warm["kernelCacheHits"] > 0
    assert warm["kernelCompiles"] == 0
    assert warm["kernelCompileMs"] == 0


def test_trace_sink_writes_jsonl_per_query(tmp_path):
    sink = str(tmp_path / "eventlog")
    sess = srt.session(**{"spark.rapids.tpu.trace.sink": sink})
    _join_query(sess).collect()
    files = os.listdir(sink)
    assert len(files) == 1 and files[0].endswith(".jsonl")
    logs = OE.read_event_log(os.path.join(sink, files[0]))
    assert len(logs) == 1
    meta, events = logs[0]
    assert events and meta["capacity"] > 0


def test_tracing_off_by_default_and_zero_events():
    # explicit default conf: a bare srt.session() would return the
    # process's active session, which another test may have profiled
    sess = srt.session(**{"spark.rapids.tpu.profile.enabled": False})
    tr = OT.get_tracer()
    tr.reset()
    _join_query(sess).collect()
    assert OT.TRACING["on"] is False
    assert tr.snapshot() == []
    assert sess.last_query_trace_summary is None


# --------------------------------------------------------------------------
# flag hygiene (satellite: session-scoped-safe process flags)
# --------------------------------------------------------------------------

def test_flags_restored_on_exception():
    from spark_rapids_tpu.sql.physical.base import PROFILING
    prev_prof, prev_trace = PROFILING["on"], OT.TRACING["on"]
    sess = srt.session(**{"spark.rapids.tpu.profile.enabled": True})
    f = F.udf(lambda a: {}[a], returnType=srt.DOUBLE)  # raises KeyError
    df = sess.create_dataframe(pa.table({"a": [1.0, 2.0]}))
    with pytest.raises(Exception):
        df.select(f(df.a).alias("b")).collect()
    assert PROFILING["on"] == prev_prof
    assert OT.TRACING["on"] == prev_trace


def test_profiling_does_not_leak_across_sessions():
    from spark_rapids_tpu.sql.physical.base import PROFILING
    sess1 = srt.session(**{"spark.rapids.tpu.profile.enabled": True})
    _join_query(sess1).collect()
    assert PROFILING["on"] is False  # restored after the query
    sess2 = srt.session(**{"spark.rapids.tpu.profile.enabled": False})
    _join_query(sess2).collect()
    assert sess2.last_query_trace_summary is None


# --------------------------------------------------------------------------
# nested TaskContext restore (satellite: execute_all clobbered the outer)
# --------------------------------------------------------------------------

def test_execute_all_restores_outer_task_context():
    from spark_rapids_tpu.sql.physical.base import TaskContext
    sess = srt.session()
    df = sess.create_dataframe(pa.table({"k": [1, 2, 3]}))
    phys = sess.physical_plan(df.groupBy("k").count())
    outer = TaskContext(99)
    TaskContext._set_current(outer)
    try:
        # a nested map-side execute_all (subquery/broadcast under an
        # outer exchange task) must restore the OUTER context, not None
        phys.execute_all(sess._conf)
        assert TaskContext.current() is outer
    finally:
        TaskContext._set_current(None)
