"""Tests for the tunnel-latency performance layer: packed single-transfer
D2H, deferred speculation validation, whole-query tail fusion, and the
adaptive OOM-guard sync policy.

Reference context: the reference's per-op kernel-launch model (SURVEY
§3.3) assumes launches are ~free; on a network-tunneled TPU each host pull
is a full round trip, so these subsystems exist to get a warm query down
to one program launch + one fetch.
"""

import numpy as np
import pyarrow as pa
import pytest


# ---------------------------------------------------------------------------
# packed D2H
# ---------------------------------------------------------------------------

class TestBulkDeviceGet:
    def test_round_trip_all_dtypes(self):
        import jax
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.convert import bulk_device_get
        rng = np.random.default_rng(7)
        tree = {
            "i64": jnp.asarray(rng.integers(-2**62, 2**62, 100)),
            "i32": jnp.asarray(rng.integers(-2**31, 2**31, 101, dtype=np.int32)),
            "i16": jnp.asarray(np.array([-5, 300, 32767], np.int16)),
            "u8": jnp.asarray(np.array([0, 255, 17], np.uint8)),
            "f32": jnp.asarray(rng.random(103).astype(np.float32)),
            "f64": jnp.asarray(rng.random(97) * rng.choice(
                [1e-30, 1.0, 1e30], 97)),
            "bool": jnp.asarray(rng.random(111) < 0.5),
            "scalar": jnp.asarray(42, jnp.int32),
            "empty": jnp.zeros(0, jnp.float64),
            "host": np.arange(5),
            "passthrough": "not-an-array",
        }
        out = bulk_device_get(tree)
        ref = jax.device_get(tree)
        for k in ref:
            if k == "passthrough":
                assert out[k] == "not-an-array"
                continue
            a, b = np.asarray(out[k]), np.asarray(ref[k])
            assert a.dtype == b.dtype, k
            assert np.array_equal(a, b), k

    def test_f64_bit_exact_on_cpu(self):
        """CPU backend: the arithmetic IEEE-754 extraction is bit-exact
        for normals/zeros/infs; NaNs canonicalize; denormals flush (DAZ,
        matching XLA's own arithmetic)."""
        import jax
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.convert import _f64_bits
        rng = np.random.default_rng(3)
        raw = rng.integers(0, 2**64, 50_000, dtype=np.uint64)
        vals = np.concatenate([raw.view(np.float64), np.array(
            [0.0, -0.0, np.inf, -np.inf, np.nan, 1.0, -1.5, 0.1, 1e300],
            np.float64)])
        got = np.asarray(jax.jit(_f64_bits)(jnp.asarray(vals)))
        exp = vals.view(np.uint64)
        nan = np.isnan(vals)
        denorm = (np.abs(vals) < 2.2250738585072014e-308) & (vals != 0) & ~nan
        exp = exp.copy()
        exp[denorm] &= np.uint64(0x8000000000000000)
        ok = (got == exp) | (nan & (got == np.uint64(0x7FF8000000000000)))
        assert ok.all()


# ---------------------------------------------------------------------------
# deferred speculation + whole-query tail fusion
# ---------------------------------------------------------------------------

def _q1ish(sess, table):
    from spark_rapids_tpu.sql import functions as F
    df = sess.create_dataframe(table)
    return (df.filter(df.v < 0.8)
            .groupBy("k")
            .agg(F.sum(F.col("v")).alias("s"),
                 F.avg(F.col("v")).alias("a"),
                 F.count("*").alias("c"))
            .orderBy("k"))


class TestFusedCollect:
    def _expected(self, table):
        pdf = table.to_pandas()
        f = pdf[pdf.v < 0.8]
        g = f.groupby("k").agg(s=("v", "sum"), a=("v", "mean"),
                               c=("v", "count")).reset_index().sort_values("k")
        return g

    def test_engages_and_matches_oracle(self, session):
        import spark_rapids_tpu.sql.physical.collect_fusion as CF
        rng = np.random.default_rng(0)
        t = pa.table({"k": rng.integers(0, 8, 5000), "v": rng.random(5000)})
        q = _q1ish(session, t)
        q.collect()  # first run: exact path, records the group-table size
        before = CF.STATS["fused_collects"]
        got = q.collect().to_pandas()
        assert CF.STATS["fused_collects"] > before, \
            "warm collect did not take the fused tail"
        exp = self._expected(t)
        assert np.array_equal(np.asarray(got["k"]), np.asarray(exp["k"]))
        assert np.array_equal(np.asarray(got["c"]), np.asarray(exp["c"]))
        assert np.allclose(np.asarray(got["s"]), np.asarray(exp["s"]))
        assert np.allclose(np.asarray(got["a"]), np.asarray(exp["a"]))

    def test_mis_speculation_reruns_correctly(self, session):
        """Same query shape with exploding group cardinality: the recorded
        size under-estimates, the deferred check fails post-fetch, and the
        session re-runs to a correct result."""
        from spark_rapids_tpu.sql.physical import speculation as SPEC
        rng = np.random.default_rng(1)
        small = pa.table({"k": rng.integers(0, 4, 2000),
                          "v": rng.random(2000)})
        q = _q1ish(session, small)
        q.collect()
        q.collect()  # records/uses spec sized for ~4 groups
        big = pa.table({"k": rng.integers(0, 3000, 20_000),
                        "v": rng.random(20_000)})
        qb = _q1ish(session, big)
        before = SPEC.STATS["reruns"]
        got = qb.collect().to_pandas()
        exp = self._expected(big)
        assert len(got) == len(exp)
        assert np.array_equal(np.asarray(got["k"]), np.asarray(exp["k"]))
        assert np.allclose(np.asarray(got["s"]), np.asarray(exp["s"]))
        # the under-speculated first attempt must have been detected
        assert SPEC.STATS["reruns"] > before or len(exp) <= 64

    def test_oom_injection_still_exercises_retry(self, session):
        """The fused tail runs under the OOM guard; injected RetryOOM on
        the exact path (first run) must not corrupt results."""
        from spark_rapids_tpu.memory.retry import arm_oom_injection
        rng = np.random.default_rng(2)
        t = pa.table({"k": rng.integers(0, 5, 3000), "v": rng.random(3000)})
        q = _q1ish(session, t)
        arm_oom_injection(retry=1)
        got = q.collect().to_pandas()
        exp = self._expected(t)
        assert np.allclose(np.asarray(got["s"]), np.asarray(exp["s"]))


class TestDeferredChecks:
    def test_registry_lifecycle(self):
        from spark_rapids_tpu.sql.physical import speculation as SPEC
        SPEC.clear()
        seen = []
        c = SPEC.register(64, None, seen.append)
        assert SPEC.unresolved() == [c]
        c.resolve(100)
        assert seen == [100]
        assert c.failed
        c.resolve(3)  # second resolve is a no-op
        assert seen == [100]
        drained = SPEC.drain()
        assert drained == [c]
        assert SPEC.unresolved() == []

    def test_deferral_flag_is_thread_local_and_off_by_default(self):
        from spark_rapids_tpu.sql.physical import speculation as SPEC
        assert not SPEC.deferral_enabled()
        SPEC.set_deferral(True)
        try:
            assert SPEC.deferral_enabled()
        finally:
            SPEC.set_deferral(False)


# ---------------------------------------------------------------------------
# adaptive OOM-guard sync
# ---------------------------------------------------------------------------

class TestOomSyncPolicy:
    def test_auto_skips_sync_when_idle(self):
        import spark_rapids_tpu.memory.oom_guard as G
        from spark_rapids_tpu.config import RapidsConf
        RapidsConf.get_global()
        # an OOM-injecting test earlier in the session may have armed the
        # defensive eager-sync window; this test asserts the IDLE policy
        G._defensive_until = 0.0
        before = dict(G.STATS)
        wrapped = G.guard_device_oom(lambda: np.float32(1.0))
        wrapped()
        assert G.STATS["lazy_dispatches"] > before["lazy_dispatches"]

    def test_injection_arms_eager_sync(self):
        import spark_rapids_tpu.memory.oom_guard as G
        from spark_rapids_tpu.memory.retry import arm_oom_injection, \
            injection_state
        arm_oom_injection(retry=1)
        try:
            assert G._should_sync()
        finally:
            injection_state().arm(0, 0)

    def test_always_mode_syncs(self):
        import spark_rapids_tpu.memory.oom_guard as G
        from spark_rapids_tpu.config import OOM_SYNC_MODE, RapidsConf
        conf = RapidsConf.get_global()
        old = conf.get(OOM_SYNC_MODE)
        conf.set(OOM_SYNC_MODE.key, "always")
        try:
            assert G._should_sync()
        finally:
            conf.set(OOM_SYNC_MODE.key, old)

    def test_real_oom_enters_defensive_window(self):
        import spark_rapids_tpu.memory.oom_guard as G

        class FakeXlaRuntimeError(Exception):
            pass
        FakeXlaRuntimeError.__name__ = "XlaRuntimeError"
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] == 1:
                raise FakeXlaRuntimeError("RESOURCE_EXHAUSTED: oom")
            return 7

        old = G._defensive_until
        try:
            assert G.guard_device_oom(flaky)() == 7
            import time
            assert G._defensive_until > time.monotonic()
            assert G._should_sync()
        finally:
            G._defensive_until = old


# ---------------------------------------------------------------------------
# speculative small-table grouping
# ---------------------------------------------------------------------------

class TestGroupIdsSmall:
    def _cols(self, keys):
        import jax.numpy as jnp

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar.column import DeviceColumn
        return [DeviceColumn(T.LONG, jnp.asarray(keys),
                             jnp.ones(len(keys), bool))]

    def test_matches_exact_kernel_when_table_fits(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops.hash_group import group_ids, \
            group_ids_small
        rng = np.random.default_rng(11)
        keys = rng.integers(0, 37, 4096)
        mask = jnp.asarray(rng.random(4096) < 0.8)
        cols = self._cols(keys)
        exact = np.asarray(group_ids(jnp, cols, mask))
        small = np.asarray(group_ids_small(jnp, cols, mask, 64))
        assert np.array_equal(exact, small)

    def test_overflow_inflates_group_count(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops.hash_group import group_ids_small
        rng = np.random.default_rng(12)
        keys = rng.permutation(4096)  # 4096 distinct keys
        mask = jnp.ones(4096, bool)
        expected = 4
        ids = np.asarray(group_ids_small(jnp, self._cols(keys), mask,
                                         expected))
        ng = int(ids.max()) + 1
        assert ng > expected, "overflow must be visible in the count"


class TestSegmentedReductionBackends:
    def test_seg2_column_split_matches_batched(self):
        """The XLA-CPU per-column scatter split must be value-identical
        to the batched 2-D scatter form."""
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import segmented as S
        rng = np.random.default_rng(9)
        n, s, out = 50_000, 6, 64
        data = jnp.asarray(rng.random((n, s)))
        ids = jnp.asarray(rng.integers(0, out + 3, n).astype(np.int64))
        a = np.asarray(S.seg_sum2(jnp, data, ids, out))
        exp = np.zeros((out, s))
        live = np.asarray(ids) < out
        np.add.at(exp, np.asarray(ids)[live], np.asarray(data)[live])
        assert np.allclose(a, exp)
        mn = np.asarray(S.seg_min2(jnp, data, ids, out, np.inf))
        mx = np.asarray(S.seg_max2(jnp, data, ids, out, -np.inf))
        for g in range(out):
            sel = np.asarray(ids) == g
            if sel.any():
                assert np.allclose(mn[g], np.asarray(data)[sel].min(axis=0))
                assert np.allclose(mx[g], np.asarray(data)[sel].max(axis=0))


class TestSyncModeNever:
    def test_never_mode_skips_all_syncs(self, session):
        import spark_rapids_tpu.memory.oom_guard as G
        from spark_rapids_tpu.config import OOM_SYNC_MODE, RapidsConf
        conf = RapidsConf.get_global()
        old = conf.get(OOM_SYNC_MODE)
        conf.set(OOM_SYNC_MODE.key, "never")
        try:
            before = G.STATS["eager_syncs"]
            wrapped = G.guard_device_oom(lambda: np.float32(2.0))
            assert wrapped() == np.float32(2.0)
            assert G.STATS["eager_syncs"] == before
        finally:
            conf.set(OOM_SYNC_MODE.key, old)


class TestTopNTailFusion:
    def test_orderby_limit_fuses_and_matches(self, session):
        import spark_rapids_tpu.sql.physical.collect_fusion as CF
        from spark_rapids_tpu.sql import functions as F
        rng = np.random.default_rng(13)
        t = pa.table({"k": rng.integers(0, 40, 20_000),
                      "v": rng.random(20_000)})
        df = session.create_dataframe(t)
        q = (df.groupBy("k").agg(F.sum(df.v).alias("s"))
             .orderBy(F.col("s").desc()).limit(6))
        plan = session.physical_plan(q).tree_string()
        assert "FusedCollect" in plan and "TakeOrdered" in plan
        q.collect()
        before = CF.STATS["fused_collects"]
        got = q.collect().to_pandas()
        assert CF.STATS["fused_collects"] > before
        exp = (t.to_pandas().groupby("k").agg(s=("v", "sum")).reset_index()
               .sort_values("s", ascending=False).head(6)
               .reset_index(drop=True))
        assert np.array_equal(np.asarray(got["k"]), np.asarray(exp["k"]))
        assert np.allclose(np.asarray(got["s"]), np.asarray(exp["s"]))

    def test_limit_with_offset_keeps_generic_path(self, session):
        from spark_rapids_tpu.sql import functions as F
        t = pa.table({"a": list(range(20))})
        df = session.create_dataframe(t)
        q = df.orderBy(F.col("a").desc()).offset(3).limit(4)
        got = sorted(q.collect().to_pandas()["a"])
        # offset paths can't take the TakeOrdered composition; results
        # must still be exact
        assert got == [13, 14, 15, 16]

    def test_sort_within_partitions_limit_not_globalized(self, session):
        """sortWithinPartitions + limit must NOT compose into a global
        TopN (the limit takes rows from the locally-sorted stream)."""
        import pyarrow as pa
        if not hasattr(session.create_dataframe(
                pa.table({"a": [1]})), "sortWithinPartitions"):
            pytest.skip("sortWithinPartitions not exposed")
        t = pa.table({"a": [5, 1, 9, 3, 7, 2]})
        df = session.create_dataframe(t, num_partitions=2)
        q = df.sortWithinPartitions("a").limit(2)
        plan = session.physical_plan(q).tree_string()
        assert "TakeOrdered" not in plan


# ---------------------------------------------------------------------------
# multi-partition tail fusion (final-mode agg, look-through range exchange)
# ---------------------------------------------------------------------------

class TestFusedCollectMultiPartition:
    def test_final_mode_fuses_first_collect(self, session):
        """Partial/exchange/final plans need NO speculation warm-up: the
        merge's group count is exact, so even a cold collect fuses."""
        import spark_rapids_tpu.sql.physical.collect_fusion as CF
        from spark_rapids_tpu.sql import functions as F
        rng = np.random.default_rng(2)
        t = pa.table({"k": rng.integers(0, 40, 30_000),
                      "v": rng.random(30_000)})
        df = session.create_dataframe(t, num_partitions=4)
        q = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"),
                                 F.count("*").alias("c"))
             .orderBy("k"))
        before = CF.STATS["fused_collects"]
        got = q.collect().to_pandas()
        assert CF.STATS["fused_collects"] > before, \
            "multi-partition cold collect did not take the fused tail"
        pdf = t.to_pandas().groupby("k").agg(
            s=("v", "sum"), c=("v", "count")).reset_index().sort_values("k")
        assert np.array_equal(np.asarray(got["k"]), np.asarray(pdf["k"]))
        assert np.array_equal(np.asarray(got["c"]), np.asarray(pdf["c"]))
        assert np.allclose(np.asarray(got["s"]), np.asarray(pdf["s"]))

    def test_high_cardinality_falls_back_with_global_order(self, session):
        """When AQE cannot coalesce to one reduce partition, the skipped
        range exchange is NOT sound — the runtime must detect live sibling
        partitions and run the original tree, preserving global order."""
        import spark_rapids_tpu.sql.physical.collect_fusion as CF
        from spark_rapids_tpu.sql import functions as F
        rng = np.random.default_rng(3)
        n = 250_000
        t = pa.table({"k": rng.integers(0, 150_000, n), "v": rng.random(n)})
        df = session.create_dataframe(t, num_partitions=4)
        q = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"))
             .orderBy("k"))
        before = CF.STATS["fallbacks"]
        got = q.collect().to_pandas()
        assert CF.STATS["fallbacks"] > before
        ks = np.asarray(got["k"])
        assert np.all(ks[1:] >= ks[:-1]), "global order broken by fusion"
        exp = t.to_pandas().groupby("k").agg(s=("v", "sum")).reset_index()
        assert len(got) == len(exp)
        assert np.allclose(np.sort(np.asarray(got["s"])),
                           np.sort(np.asarray(exp["s"])))


class TestMeasuredTransitionCost:
    def test_fixed_cost_demotes_small_query(self):
        """The measured cost model: a 65ms-per-boundary tunnel makes a
        100-row device query a loss even though per-row rates favor the
        device (VERDICT r2 #2; reference CostBasedOptimizer.scala:54)."""
        import spark_rapids_tpu as srt
        t = pa.table({"a": list(range(100)),
                      "b": [float(i) for i in range(100)]})
        sess = srt.session(**{
            "spark.rapids.sql.optimizer.enabled": True,
            "spark.rapids.sql.optimizer.transition.fixedSeconds": 0.065})
        try:
            df = sess.create_dataframe(t)
            q = df.select((df.a + 1).alias("a1"))
            rep = sess.explain(q)
            assert "CpuProject" in rep and "cost-based optimizer" in rep
            assert q.collect().to_pylist()[5]["a1"] == 6
        finally:
            srt.session(**{
                "spark.rapids.sql.optimizer.enabled": False,
                "spark.rapids.sql.optimizer.transition.fixedSeconds": -1.0})

    def test_fixed_cost_keeps_large_query(self):
        """Same 65ms boundary cost: at 8M rows the fixed latency is noise
        and the device placement must survive."""
        import spark_rapids_tpu as srt
        sess = srt.session(**{
            "spark.rapids.sql.optimizer.enabled": True,
            "spark.rapids.sql.optimizer.transition.fixedSeconds": 0.065})
        try:
            df = sess.range(8_000_000)
            rep = sess.explain(df.select((df.id * 2).alias("x")))
            assert "TpuProject" in rep
        finally:
            srt.session(**{
                "spark.rapids.sql.optimizer.enabled": False,
                "spark.rapids.sql.optimizer.transition.fixedSeconds": -1.0})

    def test_auto_measurement_is_cached(self):
        from spark_rapids_tpu.sql import optimizer as O
        O._MEASURED["rtt_s"] = None
        from spark_rapids_tpu.config import RapidsConf
        conf = RapidsConf()
        v1 = O.transition_fixed_seconds(conf)
        assert O._MEASURED["rtt_s"] is not None
        assert O.transition_fixed_seconds(conf) == v1

    def test_topn_final_mode_not_fused(self, session):
        """groupBy().agg().orderBy().limit(n) on multi-partition input:
        TakeOrderedAndProject merges all partitions itself, so final-mode
        fusion must be rejected — result is exactly n globally-first keys."""
        from spark_rapids_tpu.sql import functions as F
        rng = np.random.default_rng(4)
        n = 200_000
        t = pa.table({"k": rng.integers(0, 120_000, n), "v": rng.random(n)})
        df = session.create_dataframe(t, num_partitions=4)
        got = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"))
               .orderBy("k").limit(5).collect().to_pandas())
        exp = (t.to_pandas().groupby("k").agg(s=("v", "sum")).reset_index()
               .sort_values("k").head(5).reset_index(drop=True))
        assert len(got) == 5
        assert np.array_equal(np.asarray(got["k"]), np.asarray(exp["k"]))
        assert np.allclose(np.asarray(got["s"]), np.asarray(exp["s"]))
