"""UDF stack: compiled lambdas (udf-compiler analog), row Python UDFs,
pandas UDFs, device columnar UDFs (RapidsUDF SPI analog), mapInPandas and
applyInPandas (reference SURVEY §2.9 Python exec family)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def make_df(sess):
    t = pa.table({"a": [1., 2., 3., 4.], "b": [10., 20., 30., 40.],
                  "g": [1, 1, 2, 2]})
    return sess.create_dataframe(t), t


def test_compilable_lambda_runs_on_device(sess):
    df, t = make_df(sess)
    f1 = F.udf(lambda a, b: a * 2.0 + b if a > 2.0 else b - a,
               returnType=T.DOUBLE)
    q = df.select(f1(df.a, df.b).alias("r"))
    rep = sess.explain(q)
    assert "PythonUDF" not in rep, rep  # compiled into native expressions
    assert "cannot run" not in rep, rep
    out = [r["r"] for r in q.collect().to_pylist()]
    assert out == [9.0, 18.0, 36.0, 48.0]


def test_compiled_function_with_math(sess):
    df, t = make_df(sess)

    def my_fn(a):
        return abs(a - 3.0) + sqrt_stub(a)

    # a plain def with an unknown call must NOT compile -> host UDF
    def sqrt_stub(a):  # pragma: no cover - never called on device
        return 0.0
    f = F.udf(my_fn, returnType=T.DOUBLE)
    q = df.select(f(df.a).alias("r"))
    assert "host engine" in sess.explain(q)


def test_row_udf_on_host(sess):
    df, t = make_df(sess)
    f2 = F.udf(lambda a: float(str(a).count("1")), returnType=T.DOUBLE)
    q = df.select(f2(df.a).alias("c"))
    assert "host engine" in sess.explain(q)
    out = [r["c"] for r in q.collect().to_pylist()]
    assert out == [1.0, 0.0, 0.0, 0.0]


def test_row_udf_null_handling(sess):
    t = pa.table({"x": pa.array([1.0, None, 3.0], type=pa.float64())})
    df = sess.create_dataframe(t)
    f = F.udf(lambda x: -1.0 if x is None else x + 1, returnType=T.DOUBLE)
    out = [r["r"] for r in df.select(f(df.x).alias("r"))
           .collect().to_pylist()]
    assert out == [2.0, -1.0, 4.0]


def test_pandas_udf(sess):
    df, t = make_df(sess)
    p1 = F.pandas_udf(lambda s: s * 10, returnType=T.DOUBLE)
    out = [r["p"] for r in df.select(p1(df.a).alias("p"))
           .collect().to_pylist()]
    assert out == [10., 20., 30., 40.]


def test_pandas_udf_two_args(sess):
    df, t = make_df(sess)
    p = F.pandas_udf(lambda a, b: a + b.cumsum() * 0, returnType=T.DOUBLE)
    out = [r["p"] for r in df.select(p(df.a, df.b).alias("p"))
           .collect().to_pylist()]
    assert out == [1., 2., 3., 4.]


def test_device_udf_traceable(sess):
    df, t = make_df(sess)

    def saxpy(xp, a, b):
        (ad, av), (bd, bv) = a, b
        return ad * 2.0 + bd, av & bv
    d1 = F.device_udf(saxpy, returnType=T.DOUBLE)
    q = df.select(d1(df.a, df.b).alias("s"))
    assert "cannot run" not in sess.explain(q)
    out = [r["s"] for r in q.collect().to_pylist()]
    assert out == [12., 24., 36., 48.]


def test_map_in_pandas(sess):
    df, t = make_df(sess)

    def mapper(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["a2"] = pdf["a"] * 100
            yield pdf[["a2"]]
    out = df.mapInPandas(mapper, "a2 double").collect().to_pylist()
    assert sorted(r["a2"] for r in out) == [100., 200., 300., 400.]


def test_apply_in_pandas_groups(sess):
    df, t = make_df(sess)

    def norm(pdf):
        pdf = pdf.copy()
        pdf["z"] = pdf["a"] - pdf["a"].mean()
        return pdf[["g", "z"]]
    out = (df.groupBy("g").applyInPandas(norm, "g long, z double")
           .orderBy("g", "z").collect().to_pylist())
    assert [r["z"] for r in out] == [-0.5, 0.5, -0.5, 0.5]


def test_apply_in_pandas_multi_partition(sess):
    rng = np.random.default_rng(5)
    n = 3000
    t = pa.table({"g": rng.integers(0, 20, n), "v": rng.random(n)})
    df = sess.create_dataframe(t, num_partitions=4)

    def stats(pdf):
        return pd.DataFrame({"g": [pdf["g"].iloc[0]],
                             "s": [pdf["v"].sum()],
                             "c": [float(len(pdf))]})
    got = (df.groupBy("g").applyInPandas(stats, "g long, s double, c double")
           .orderBy("g").collect().to_pandas())
    exp = (t.to_pandas().groupby("g")
           .agg(s=("v", "sum"), c=("v", "size")).reset_index())
    assert np.array_equal(got["g"], exp["g"])
    assert np.allclose(got["s"], exp["s"])
    assert np.array_equal(got["c"], exp["c"].astype(float))


def test_two_lambdas_one_line_not_miscompiled(sess):
    df, t = make_df(sess)
    fs = [F.udf(lambda x: x + 1.0, returnType=T.DOUBLE), F.udf(lambda x: x * 2.0, returnType=T.DOUBLE)]  # noqa: E501
    out = df.select(fs[0](df.a).alias("p"), fs[1](df.a).alias("q")) \
        .collect().to_pylist()
    assert [r["p"] for r in out] == [2.0, 3.0, 4.0, 5.0]
    assert [r["q"] for r in out] == [2.0, 4.0, 6.0, 8.0]


def test_truthy_and_or_not_compiled(sess):
    """Python and/or over non-boolean operands returns operands — must
    fall back to the host UDF, not compile to SQL booleans."""
    df, t = make_df(sess)
    f = F.udf(lambda a, b: a and b, returnType=T.DOUBLE)
    q = df.select(f(df.a, df.b).alias("r"))
    assert "host engine" in sess.explain(q)
    out = [r["r"] for r in q.collect().to_pylist()]
    assert out == [10., 20., 30., 40.]  # a is truthy -> b


def test_compiled_udf_respects_return_type(sess):
    df, t = make_df(sess)
    f = F.udf(lambda a: a > 2.0, returnType=T.DOUBLE)
    out = df.select(f(df.a).alias("r")).collect()
    import pyarrow as pa
    assert out.schema.field("r").type == pa.float64()
    assert [r["r"] for r in out.to_pylist()] == [0.0, 0.0, 1.0, 1.0]


def test_row_udf_exception_propagates(sess):
    df, t = make_df(sess)
    f = F.udf(lambda a: {}[a], returnType=T.DOUBLE)  # KeyError per row
    with pytest.raises(KeyError):
        df.select(f(df.a).alias("r")).collect()


def test_pandas_udf_wrong_length_raises(sess):
    df, t = make_df(sess)
    p = F.pandas_udf(lambda s: pd.Series([s.sum()]), returnType=T.DOUBLE)
    with pytest.raises(ValueError, match="length"):
        df.select(p(df.a).alias("r")).collect()


def test_apply_in_pandas_rejects_expression_keys(sess):
    df, t = make_df(sess)
    with pytest.raises(ValueError, match="plain columns"):
        df.groupBy(df.g + 1).applyInPandas(lambda p: p, "g long")


def test_cogroup_apply_in_pandas(sess):
    left = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 3], "v": [1.0, 2.0, 3.0, 4.0]}))
    right = sess.create_dataframe(pa.table({
        "k": [1, 2, 2, 4], "w": [10.0, 20.0, 30.0, 40.0]}))

    def summarize(l, r):
        k = l["k"].iloc[0] if len(l) else r["k"].iloc[0]
        return pd.DataFrame({"k": [k], "lv": [l["v"].sum() if len(l) else 0.0],
                             "rw": [r["w"].sum() if len(r) else 0.0]})
    got = (left.groupBy("k").cogroup(right.groupBy("k"))
           .applyInPandas(summarize, "k long, lv double, rw double")
           .orderBy("k").collect().to_pylist())
    assert got == [
        {"k": 1, "lv": 3.0, "rw": 10.0},
        {"k": 2, "lv": 3.0, "rw": 50.0},
        {"k": 3, "lv": 4.0, "rw": 0.0},
        {"k": 4, "lv": 0.0, "rw": 40.0},
    ]


def test_cogroup_multi_partition(sess):
    rng = np.random.default_rng(8)
    n = 2000
    left = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 30, n), "v": rng.random(n)}),
        num_partitions=4)
    right = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 30, n), "w": rng.random(n)}),
        num_partitions=3)

    def stats(l, r):
        k = l["k"].iloc[0] if len(l) else r["k"].iloc[0]
        return pd.DataFrame({"k": [k], "c": [float(len(l) + len(r))]})
    got = (left.groupBy("k").cogroup(right.groupBy("k"))
           .applyInPandas(stats, "k long, c double")
           .orderBy("k").collect().to_pandas())
    import collections
    cnt = collections.Counter(
        list(left.collect()["k"].to_pylist())
        + list(right.collect()["k"].to_pylist()))
    assert dict(zip(got["k"], got["c"])) == {
        k: float(v) for k, v in cnt.items()}


def test_cogroup_different_key_names(sess):
    left = sess.create_dataframe(pa.table({
        "a": [1, 2], "v": [1.0, 2.0]}))
    right = sess.create_dataframe(pa.table({
        "b": [2, 3], "w": [20.0, 30.0]}))

    def f(l, r):
        k = l["a"].iloc[0] if len(l) else r["b"].iloc[0]
        return pd.DataFrame({"k": [k],
                             "lv": [l["v"].sum() if len(l) else 0.0],
                             "rw": [r["w"].sum() if len(r) else 0.0]})
    got = (left.groupBy("a").cogroup(right.groupBy("b"))
           .applyInPandas(f, "k long, lv double, rw double")
           .orderBy("k").collect().to_pylist())
    assert got == [{"k": 1, "lv": 1.0, "rw": 0.0},
                   {"k": 2, "lv": 2.0, "rw": 20.0},
                   {"k": 3, "lv": 0.0, "rw": 30.0}]


def test_cogroup_empty_side_has_full_schema(sess):
    left = sess.create_dataframe(pa.table({
        "k": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]}),
        num_partitions=2)
    right = sess.create_dataframe(pa.table({
        "k": [1], "w": [10.0]}))

    def f(l, r):
        # touching the non-key column of a possibly-empty side must work
        return pd.DataFrame({"k": [l["k"].iloc[0] if len(l)
                                   else r["k"].iloc[0]],
                             "rw": [float(r["w"].sum())]})
    got = (left.groupBy("k").cogroup(right.groupBy("k"))
           .applyInPandas(f, "k long, rw double")
           .orderBy("k").collect().to_pylist())
    assert got == [{"k": 1, "rw": 10.0}, {"k": 2, "rw": 0.0},
                   {"k": 3, "rw": 0.0}, {"k": 4, "rw": 0.0}]


# --- grouped-agg pandas UDFs (GpuAggregateInPandasExec analog) -------------

def test_grouped_agg_pandas_udf(sess):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    df = sess.create_dataframe(pa.table({
        "k": ["a", "a", "b", "b", "b"],
        "v": [1.0, 2.0, 3.0, 4.0, 5.0]}), num_partitions=2)
    wmean = F.pandas_udf(lambda s: float(s.mean()), T.DOUBLE,
                         functionType="grouped_agg")
    out = df.groupBy("k").agg(wmean(df.v).alias("m")).orderBy("k").collect()
    assert out.to_pylist() == [{"k": "a", "m": 1.5}, {"k": "b", "m": 4.0}]


def test_grouped_agg_pandas_udf_multi_arg_multi_udf(sess):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 2],
        "x": [1.0, 3.0, 10.0, 30.0],
        "w": [1.0, 3.0, 1.0, 1.0]}), num_partitions=3)
    wavg = F.pandas_udf(lambda v, w: float((v * w).sum() / w.sum()),
                        T.DOUBLE, functionType="grouped_agg")
    mx = F.pandas_udf(lambda v: float(v.max()), T.DOUBLE,
                      functionType="grouped_agg")
    out = (df.groupBy("k")
           .agg(wavg(df.x, df.w).alias("wa"), mx(df.x).alias("mx"))
           .orderBy("k").collect())
    assert out.to_pylist() == [
        {"k": 1, "wa": 2.5, "mx": 3.0}, {"k": 2, "wa": 20.0, "mx": 30.0}]


def test_grouped_agg_udf_rejects_mixing_with_builtin(sess):
    import pyarrow as pa
    import pytest as _pytest
    from spark_rapids_tpu import types as T
    df = sess.create_dataframe(pa.table({"k": [1], "v": [1.0]}))
    g = F.pandas_udf(lambda s: float(s.sum()), T.DOUBLE,
                     functionType="grouped_agg")
    with _pytest.raises(ValueError, match="mixed"):
        df.groupBy("k").agg(g(df.v).alias("a"),
                            F.sum(F.col("v")).alias("b"))


def test_grouped_agg_udf_expression_args(sess):
    """UDF arguments may be full expressions (pre-projected by the
    planner), not just plain columns."""
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2], "v": [1.0, 2.0, 10.0]}), num_partitions=2)
    s = F.pandas_udf(lambda x: float(x.sum()), T.DOUBLE,
                     functionType="grouped_agg")
    out = (df.groupBy("k").agg(s(df.v * 2.0 + 1.0).alias("t"))
           .orderBy("k").collect())
    assert out.to_pylist() == [{"k": 1, "t": 8.0}, {"k": 2, "t": 21.0}]


def test_python_worker_semaphore_bounds_concurrency(sess):
    """Parallel user-Python sections never exceed the configured cap."""
    import pyarrow as pa
    import threading
    from spark_rapids_tpu.memory import python_worker as PW
    from spark_rapids_tpu import types as T
    PW.PythonWorkerSemaphore.shutdown()
    s = srt.session(**{"spark.rapids.python.concurrentPythonWorkers": 2})
    PW.STATS.update(acquires=0, peak=0, current=0)
    df = s.create_dataframe(pa.table({
        "k": list(range(8)), "v": [float(i) for i in range(8)]}),
        num_partitions=8)

    import time as _t
    def slow(pdf):
        _t.sleep(0.05)
        return pdf

    out = df.groupBy("k").applyInPandas(
        slow, T.StructType((T.StructField("k", T.LONG, True),
                            T.StructField("v", T.DOUBLE, True))))
    # run partitions on threads to create real concurrency
    results = []
    threads = [threading.Thread(target=lambda: results.append(
        out.collect().num_rows)) for _ in range(2)]
    for t in threads: t.start()
    for t in threads: t.join()
    assert results == [8, 8]
    # one acquire per python section (AQE may coalesce partitions, so the
    # count is per-exec-invocation, not per input partition)
    assert PW.STATS["acquires"] >= 2
    assert PW.STATS["peak"] <= 2
    PW.PythonWorkerSemaphore.shutdown()


def test_grouped_agg_udf_global_and_aliased_key(sess):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2], "v": [1.0, 2.0, 9.0]}), num_partitions=2)
    s = F.pandas_udf(lambda x: float(x.sum()), T.DOUBLE,
                     functionType="grouped_agg")
    # global aggregation (no keys)
    out = df.agg(s(df.v).alias("t")).collect()
    assert out.to_pylist() == [{"t": 12.0}]
    # aliased grouping key
    out2 = (df.groupBy(df.k.alias("kk")).agg(s(df.v).alias("t"))
            .orderBy("kk").collect())
    assert out2.to_pylist() == [{"kk": 1, "t": 3.0}, {"kk": 2, "t": 9.0}]


# ---------------------------------------------------------------------------
# out-of-process worker pool (python/rapids/daemon.py analog, VERDICT r3 #9)
# ---------------------------------------------------------------------------

def test_udf_worker_crash_fails_task_not_session(sess):
    """A UDF that kills its interpreter takes down its WORKER process;
    the task fails with WorkerCrashed, and the session keeps serving
    queries afterwards (the done-criteria of VERDICT r3 #9)."""
    import pytest as _pytest
    from spark_rapids_tpu.pyworker import STATS, WorkerCrashed
    t = pa.table({"x": [1.0, 2.0, 3.0]})
    df = sess.create_dataframe(t)

    def killer(it):
        import os
        os._exit(42)
        yield  # pragma: no cover

    crashes0 = STATS["crashes"]
    with _pytest.raises(Exception) as ei:
        df.mapInPandas(killer, T.StructType((
            T.StructField("x", T.DOUBLE, True),))).collect()
    assert isinstance(ei.value, WorkerCrashed) or \
        "worker died" in str(ei.value)
    assert STATS["crashes"] == crashes0 + 1
    # session is alive: both a plain query and a fresh UDF still work
    assert df.count() == 3
    out = df.mapInPandas(
        lambda it: (p.assign(x=p.x * 2) for p in it),
        T.StructType((T.StructField("x", T.DOUBLE, True),))
    ).collect().to_pandas()
    assert sorted(out["x"]) == [2.0, 4.0, 6.0]


def test_udf_worker_error_carries_traceback(sess):
    import pytest as _pytest
    t = pa.table({"x": [1.0]})
    df = sess.create_dataframe(t)

    def boom(it):
        raise RuntimeError("sentinel-broke-here")
        yield  # pragma: no cover

    with _pytest.raises(Exception, match="sentinel-broke-here"):
        df.mapInPandas(boom, T.StructType((
            T.StructField("x", T.DOUBLE, True),))).collect()


def test_udf_worker_print_does_not_corrupt_protocol(sess):
    t = pa.table({"x": [1.0, 2.0]})
    df = sess.create_dataframe(t)

    def chatty(it):
        for p in it:
            print("user print must go to stderr, not the frame pipe")
            yield p

    out = df.mapInPandas(chatty, T.StructType((
        T.StructField("x", T.DOUBLE, True),))).collect()
    assert out.num_rows == 2


def test_udf_worker_pool_reuse_and_gating(sess):
    """Workers are reused across jobs, and the pool never holds more
    live workers than the concurrentPythonWorkers cap."""
    from spark_rapids_tpu.pyworker import STATS, PythonWorkerPool
    t = pa.table({"x": [1.0, 2.0]})
    df = sess.create_dataframe(t)
    schema = T.StructType((T.StructField("x", T.DOUBLE, True),))
    spawned0 = STATS["spawned"]
    for _ in range(3):
        df.mapInPandas(lambda it: it, schema).collect()
    assert STATS["spawned"] - spawned0 <= 1, "workers were not reused"
    pool = PythonWorkerPool.get(sess._conf)
    assert STATS["peak_workers"] <= pool.capacity


def test_udf_in_process_kill_switch(sess):
    """worker.isolated=false restores the in-process path (object
    identity survives, no Arrow round-trip)."""
    sess.conf.set("spark.rapids.python.worker.isolated", False)
    try:
        from spark_rapids_tpu.pyworker import STATS
        jobs0 = STATS["jobs"]
        t = pa.table({"x": [1.0]})
        df = sess.create_dataframe(t)
        out = df.mapInPandas(
            lambda it: (p for p in it),
            T.StructType((T.StructField("x", T.DOUBLE, True),))
        ).collect()
        assert out.num_rows == 1
        assert STATS["jobs"] == jobs0  # pool untouched
    finally:
        sess.conf.set("spark.rapids.python.worker.isolated", True)


def test_udf_worker_reraises_original_exception_type(sess):
    """User exceptions cross the worker boundary with their ORIGINAL
    type (picklable case), so `except ValueError:` written against the
    in-process path keeps working — and the worker survives user errors
    (no respawn per exception)."""
    import pytest as _pytest
    from spark_rapids_tpu.pyworker import STATS
    t = pa.table({"x": [1.0]})
    df = sess.create_dataframe(t)
    schema = T.StructType((T.StructField("x", T.DOUBLE, True),))

    def raiser(it):
        raise ValueError("typed-error-sentinel")
        yield  # pragma: no cover

    df.mapInPandas(lambda it: it, schema).collect()  # warm a worker
    spawned0 = STATS["spawned"]
    with _pytest.raises(ValueError, match="typed-error-sentinel"):
        df.mapInPandas(raiser, schema).collect()
    df.mapInPandas(lambda it: it, schema).collect()
    assert STATS["spawned"] == spawned0, "user error must not kill worker"


def test_apply_in_pandas_group_gets_range_index(sess):
    """PySpark contract: each applyInPandas group arrives with a fresh
    RangeIndex (g.loc[0] works for every group) — review r4 finding."""
    t = pa.table({"k": [1, 1, 2, 2, 2], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    df = sess.create_dataframe(t)

    def first_row(g):
        return g.loc[[0]]  # KeyError unless the index was reset

    out = (df.groupBy("k").applyInPandas(first_row, T.StructType((
        T.StructField("k", T.LONG, True),
        T.StructField("v", T.DOUBLE, True))))
        .collect().to_pandas().sort_values("k"))
    assert len(out) == 2
