"""Whole-stage XLA compilation (ISSUE 7, docs/whole_stage.md): terminal
stage formation (aggregate + join probe), fused-vs-killswitched bit
parity over encoded x parallelism, lazy program registration, donation
safety (retention registry), and the coverage/dispatch metrics."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import retention
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical.fusion import FusedStageExec
from spark_rapids_tpu.sql.physical.aggregate import HashAggregateExec
from spark_rapids_tpu.sql.physical.join import BaseJoinExec


ROWS = 4000


def _tables():
    rng = np.random.default_rng(17)
    cats = [f"cat_{i:02d}" for i in range(12)]
    fact = pa.table({
        "k": rng.integers(0, 7, ROWS).astype(np.int64),
        "ck": pa.array([cats[i] for i in rng.integers(0, 12, ROWS)]),
        "q": rng.integers(0, 100, ROWS).astype(np.int64),
        "v": rng.random(ROWS),
        "fk": rng.integers(0, 200, ROWS).astype(np.int64),
    })
    # dim covers only half the key space so anti/outer joins have teeth
    dim = pa.table({"pk": np.arange(0, 200, 2, dtype=np.int64),
                    "w": rng.random(100)})
    return fact, dim


FACT, DIM = _tables()


def _session(whole_stage=True, fusion=True, encoded=False, parallelism=1,
             **extra):
    over = {
        "spark.rapids.tpu.sql.fusion.enabled": fusion,
        "spark.rapids.tpu.sql.wholeStage.enabled": whole_stage,
        "spark.rapids.tpu.sql.encoded.enabled": encoded,
        "spark.rapids.tpu.task.parallelism": parallelism,
    }
    over.update(extra)
    return srt.session(conf=RapidsConf.get_global().copy(over))


def _canon(table: pa.Table) -> pd.DataFrame:
    df = table.to_pandas()
    return df.sort_values(list(df.columns), kind="mergesort") \
        .reset_index(drop=True)


def _q_filter_project_agg(sess):
    f = sess.create_dataframe(FACT, num_partitions=4)
    return (f.filter(F.col("q") < 60)
            .withColumn("y", F.col("v") * 2.0)
            .groupBy("k")
            .agg(F.sum(F.col("y")).alias("sy"), F.count("*").alias("c"))
            .orderBy("k"))


def _q_complete_agg(sess):
    f = sess.create_dataframe(FACT)  # single partition -> complete mode
    return (f.filter(F.col("q") >= 20).groupBy("k")
            .agg(F.sum(F.col("v")).alias("sv")).orderBy("k"))


def _q_map_chain(sess):
    f = sess.create_dataframe(FACT, num_partitions=2)
    return (f.filter(F.col("q") < 80)
            .withColumn("y", F.col("v") + 1.0)
            .filter(F.col("v") < 0.9)
            .select("k", "y"))


def _q_probe_join(sess, how="inner"):
    f = sess.create_dataframe(FACT, num_partitions=4)
    d = sess.create_dataframe(DIM)
    return (f.filter(F.col("q") < 50)
            .withColumn("y", F.col("v") * 3.0)
            .join(d, f.fk == d.pk, how))


def _q_encoded_filter_agg(sess):
    f = sess.create_dataframe(FACT, num_partitions=4)
    return (f.filter(F.col("ck") <= "cat_07").groupBy("ck")
            .agg(F.sum(F.col("q")).alias("sq"), F.count("*").alias("n"))
            .orderBy("ck"))


# --------------------------------------------------------------------------
# plan shape
# --------------------------------------------------------------------------

def _find(plan, pred):
    out = []
    stack = [plan]
    while stack:
        n = stack.pop()
        if pred(n):
            out.append(n)
        stack.extend(n.children)
    return out


def test_agg_terminal_stage_in_plan():
    sess = _session()
    plan = sess.physical_plan(_q_filter_project_agg(sess))
    stages = _find(plan, lambda n: isinstance(n, FusedStageExec)
                   and isinstance(n.terminal, HashAggregateExec))
    assert stages, plan.tree_string()
    st = stages[0]
    assert st.terminal.mode == "partial"
    assert len(st.members) == 2  # filter + project
    assert st.terminal._pre_steps  # chain absorbed into the partial kernel


def test_probe_terminal_in_plan():
    sess = _session()
    plan = sess.physical_plan(_q_probe_join(sess))
    joins = _find(plan, lambda n: isinstance(n, BaseJoinExec))
    assert joins and joins[0]._probe_steps, plan.tree_string()
    assert "fusedProbe" in joins[0].simple_string()


def test_killswitch_reverts_plan():
    sess = _session(whole_stage=False)
    plan = sess.physical_plan(_q_filter_project_agg(sess))
    assert not _find(plan, lambda n: isinstance(n, FusedStageExec)
                     and n.terminal is not None)
    joins = _find(sess.physical_plan(_q_probe_join(sess)),
                  lambda n: isinstance(n, BaseJoinExec))
    assert joins and not joins[0]._probe_steps
    # fusion fully off: no FusedStage nodes at all
    off = _session(fusion=False)
    plan = off.physical_plan(_q_map_chain(off))
    assert not _find(plan, lambda n: isinstance(n, FusedStageExec))


def test_lazy_plan_registers_no_kernels():
    """Plan construction (incl. terminal absorption) must not touch the
    kernel cache — AQE re-plans and CPU-fallback discards pay nothing."""
    from spark_rapids_tpu.sql.physical.kernel_cache import cache_stats
    sess = _session()
    before = cache_stats()["misses"]
    sess.physical_plan(_q_filter_project_agg(sess))
    sess.physical_plan(_q_probe_join(sess))
    sess.physical_plan(_q_map_chain(sess))
    assert cache_stats()["misses"] == before


# --------------------------------------------------------------------------
# fused-vs-killswitched bit-parity matrix
# --------------------------------------------------------------------------

@pytest.mark.parametrize("encoded", [False, True])
@pytest.mark.parametrize("parallelism", [1, 4])
def test_parity_matrix(encoded, parallelism):
    shapes = {
        "filter_project_agg": _q_filter_project_agg,
        "complete_agg": _q_complete_agg,
        "map_chain": _q_map_chain,
        "probe_join": _q_probe_join,
        "encoded_filter_agg": _q_encoded_filter_agg,
    }
    on = _session(encoded=encoded, parallelism=parallelism)
    off = _session(whole_stage=False, fusion=False, encoded=encoded,
                   parallelism=parallelism)
    for name, mk in shapes.items():
        got = _canon(mk(on).collect())
        exp = _canon(mk(off).collect())
        pd.testing.assert_frame_equal(got, exp, check_exact=True), name


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_probe_join_parity_by_type(how):
    on = _session()
    off = _session(whole_stage=False, fusion=False)
    got = _canon(_q_probe_join(on, how).collect())
    exp = _canon(_q_probe_join(off, how).collect())
    assert len(exp) > 0  # the shape must exercise real rows
    pd.testing.assert_frame_equal(got, exp, check_exact=True)


# --------------------------------------------------------------------------
# donation safety
# --------------------------------------------------------------------------

def _device_batch(n=64):
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import DeviceColumn
    col = DeviceColumn(T.LONG, jnp.arange(n, dtype=jnp.int64),
                       jnp.ones(n, dtype=bool))
    return ColumnarBatch.make(["a"], [col], n)


def test_retention_registry_unit():
    b = _device_batch()
    assert not retention.is_pinned(b)
    ok, why = retention.may_donate(b)
    assert not ok and why == "not_transient"
    retention.mark_transient(b)
    ok, why = retention.may_donate(b)
    assert ok
    retention.pin_batch(b)
    retention.pin_batch(b)
    ok, why = retention.may_donate(b)
    assert not ok and why == "pinned"
    retention.unpin_batch(b)
    assert retention.is_pinned(b)  # refcounted
    retention.unpin_batch(b)
    assert not retention.is_pinned(b)
    assert retention.may_donate(b)[0]


def test_retention_declines_encoded():
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.encoded import (DictEncodedColumn,
                                                   dictionary_from_values)
    n = 16
    d = dictionary_from_values(T.STRING, [b"a", b"b", b"c"])
    enc = DictEncodedColumn(T.STRING, jnp.zeros(n, dtype=jnp.int32), d,
                            jnp.ones(n, dtype=bool))
    b = ColumnarBatch.make(["s"], [enc], n)
    retention.mark_transient(b)
    ok, why = retention.may_donate(b)
    assert not ok and why == "encoded"


def test_donated_batch_never_reachable_from_retainers():
    """The satellite's safety proof: each retention tier pins, and a
    pinned batch is never donation-eligible."""
    # spill tier
    from spark_rapids_tpu.memory.spill import SpillableColumnarBatch
    b = retention.mark_transient(_device_batch())
    sb = SpillableColumnarBatch.create(b)
    try:
        assert retention.is_pinned(b)
        assert retention.may_donate(b) == (False, "pinned")
    finally:
        sb.close()
    # prefetch queue / transfer stager contract: pin while enqueued
    b2 = retention.mark_transient(_device_batch())
    retention.pin_batch(b2)  # what AsyncPrefetchExec does on put
    assert retention.may_donate(b2) == (False, "pinned")
    retention.unpin_batch(b2)  # consumer handoff
    assert retention.may_donate(b2)[0]
    # broadcast: the cached broadcast batch is pinned
    from spark_rapids_tpu.sql.physical.base import TaskContext
    from spark_rapids_tpu.sql.physical.exchange import BroadcastExchangeExec
    from spark_rapids_tpu.sql.physical.basic import InMemoryScanExec
    from spark_rapids_tpu.sql.expressions.core import AttributeReference
    from spark_rapids_tpu import types as T
    scan = InMemoryScanExec([AttributeReference("pk", T.LONG, False),
                             AttributeReference("w", T.DOUBLE, True)],
                            [DIM])
    bx = BroadcastExchangeExec(scan)
    bcast = bx.broadcast_batch(TaskContext(0))
    assert retention.is_pinned(bcast)
    retention.mark_transient(bcast)
    assert retention.may_donate(bcast) == (False, "pinned")


def test_scan_cached_uploads_are_pinned_and_declined():
    """A fused stage directly above an in-memory scan must never donate
    the relation's resident batches."""
    sess = _session()
    f = sess.create_dataframe(FACT, num_partitions=2)
    q = (f.filter(F.col("q") < 70).filter(F.col("v") < 0.95)
         .select("k", "v"))
    before = retention.stats_snapshot()
    got = _canon(q.collect())
    m = sess.last_query_metrics
    assert m.get("wholeStageDonatedBatches", 0) == 0
    assert m.get("wholeStageDonationDeclined", 0) > 0
    # and the result still matches the unfused run
    off = _session(whole_stage=False, fusion=False)
    f2 = off.create_dataframe(FACT, num_partitions=2)
    exp = _canon(f2.filter(F.col("q") < 70).filter(F.col("v") < 0.95)
                 .select("k", "v").collect())
    pd.testing.assert_frame_equal(got, exp, check_exact=True)


def test_donation_applies_to_fresh_batches():
    """Range batches are fresh single-owner buffers: the map stage above
    them donates (the decision path runs on every backend; buffers are
    physically reclaimed only on real devices)."""
    sess = _session()
    q = (sess.range(0, 30_000, num_slices=2)
         .filter(F.col("id") % 3 == 0)
         .select((F.col("id") * 2).alias("d")))
    got = q.collect()
    assert sess.last_query_metrics.get("wholeStageDonatedBatches", 0) > 0
    noden = _session(**{
        "spark.rapids.tpu.sql.wholeStage.donation.enabled": False})
    q2 = (noden.range(0, 30_000, num_slices=2)
          .filter(F.col("id") % 3 == 0)
          .select((F.col("id") * 2).alias("d")))
    exp = q2.collect()
    assert noden.last_query_metrics.get("wholeStageDonatedBatches", 0) == 0
    assert got.to_pylist() == exp.to_pylist()


# --------------------------------------------------------------------------
# metrics / dispatch evidence
# --------------------------------------------------------------------------

def test_coverage_and_dispatch_metrics():
    on = _session()
    q = _q_filter_project_agg(on)
    q.collect()
    q.collect()  # warm: speculation recorded -> fused partial path
    m_on = dict(on.last_query_metrics)
    assert m_on["wholeStageOps"] >= 3  # filter + project + agg terminal
    assert m_on.get("deviceDispatches", 0) > 0
    off = _session(whole_stage=False, fusion=False)
    q2 = _q_filter_project_agg(off)
    q2.collect()
    q2.collect()
    m_off = dict(off.last_query_metrics)
    assert m_off["unfusedOps"] >= 3
    assert m_off["wholeStageOps"] == 0
    # the acceptance ratio: stage-scope dispatches drop >= 3x warm
    assert m_off["stageOpDispatches"] >= 3 * m_on["stageOpDispatches"], \
        (m_off["stageOpDispatches"], m_on["stageOpDispatches"])


def test_stage_trace_category():
    sess = _session(**{"spark.rapids.tpu.trace.sink": "memory"})
    _q_map_chain(sess).collect()
    events = sess._last_trace_events
    assert any(ev.get("cat") == "stage" for ev in events)
    summary = sess.last_query_trace_summary
    assert summary.get("stage_count", 0) > 0
    assert summary.get("device_dispatches", 0) > 0


def test_collect_tail_fusion_still_engages():
    """Regression: the FusedStage wrapper around a complete aggregate
    must stay transparent to the collect-tail fusion pass."""
    from spark_rapids_tpu.sql.physical import collect_fusion as CF
    sess = _session()
    q = _q_complete_agg(sess)
    before = CF.STATS["fused_collects"]
    q.collect()
    q.collect()  # second run has a recorded speculation -> fused tail
    assert CF.STATS["fused_collects"] > before
