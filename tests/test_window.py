"""Window exec + expression tests — reference coverage model:
integration_tests window_function_test.py (rank family, lead/lag, frame
aggregations, range frames), cross-checked against pandas and the host
engine."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import Window
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def make_df(sess, n=500, seed=3, with_nulls=True):
    rng = np.random.default_rng(seed)
    vals = rng.integers(-50, 50, n).astype("float64")
    nulls = (rng.random(n) < 0.1) if with_nulls else np.zeros(n, bool)
    t = pa.table({
        "g": pa.array(rng.integers(0, 7, n), type=pa.int64()),
        "o": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "v": pa.array([None if nu else float(v)
                       for v, nu in zip(vals, nulls)], type=pa.float64()),
        "u": pa.array(np.arange(n), type=pa.int64()),  # unique tiebreak
    })
    return sess.create_dataframe(t), t.to_pandas()


def both_engines(df, sort_cols):
    sess = df._session
    tpu = df.collect().to_pandas().sort_values(sort_cols).reset_index(drop=True)
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        cpu = df.collect().to_pandas().sort_values(sort_cols).reset_index(drop=True)
    finally:
        sess.conf.set("spark.rapids.sql.enabled", True)
    pd.testing.assert_frame_equal(tpu, cpu, check_dtype=False)
    return tpu


def test_rank_family(sess):
    df, pdf = make_df(sess)
    w = Window.partitionBy("g").orderBy("o")
    out = df.select(
        df.u, df.g, df.o,
        F.row_number().over(w).alias("rn"),
        F.rank().over(w).alias("rk"),
        F.dense_rank().over(w).alias("dr"),
        F.percent_rank().over(w).alias("pr"),
        F.cume_dist().over(w).alias("cd"),
        F.ntile(4).over(w).alias("nt"),
    )
    got = both_engines(out, ["u"])

    g = pdf.sort_values(["g", "o", "u"]).groupby("g")
    exp = pdf.copy()
    exp["rk"] = g["o"].rank(method="min").astype(int)
    exp["dr"] = g["o"].rank(method="dense").astype(int)
    exp = exp.sort_values("u").reset_index(drop=True)
    assert (got["rk"] == exp["rk"]).all()
    assert (got["dr"] == exp["dr"]).all()
    # row_number is unique 1..len within each partition
    for _, grp in got.groupby("g"):
        assert sorted(grp["rn"]) == list(range(1, len(grp) + 1))
    # percent_rank = (rank-1)/(n-1)
    sizes = got.groupby("g")["u"].transform("count")
    expected_pr = np.where(sizes > 1, (got["rk"] - 1) / (sizes - 1), 0.0)
    assert np.allclose(got["pr"], expected_pr)
    # cume_dist in (0, 1]
    assert ((got["cd"] > 0) & (got["cd"] <= 1)).all()
    # ntile buckets 1..4
    assert got["nt"].between(1, 4).all()


def test_lead_lag(sess):
    df, pdf = make_df(sess)
    w = Window.partitionBy("g").orderBy("u")
    out = df.select(
        df.u, df.g, df.v,
        F.lag(df.v, 1).over(w).alias("lag1"),
        F.lead(df.v, 2).over(w).alias("lead2"),
        F.lag(df.v, 1, -999.0).over(w).alias("lag_d"),
    )
    got = both_engines(out, ["u"])
    exp = pdf.sort_values(["g", "u"]).copy()
    grp = exp.groupby("g")["v"]
    exp["lag1"] = grp.shift(1)
    exp["lead2"] = grp.shift(-2)
    exp["lag_d"] = grp.shift(1).where(grp.shift(1).notna() |
                                      grp.transform("cumcount").eq(0) == False)
    exp = exp.sort_values("u").reset_index(drop=True)
    assert np.allclose(got["lag1"].fillna(1e18), exp["lag1"].fillna(1e18))
    assert np.allclose(got["lead2"].fillna(1e18), exp["lead2"].fillna(1e18))
    # default fills only out-of-partition positions (first row per group)
    first_rows = exp.groupby("g")["u"].transform("min") == exp["u"]
    assert (got.loc[first_rows.values, "lag_d"] == -999.0).all()


def test_running_and_sliding_aggs(sess):
    df, pdf = make_df(sess)
    # deterministic order: unique key u
    running = Window.partitionBy("g").orderBy("u")
    sliding = Window.partitionBy("g").orderBy("u").rowsBetween(-2, 2)
    out = df.select(
        df.u, df.g, df.v,
        F.sum(df.v).over(running).alias("rsum"),
        F.count(df.v).over(running).alias("rcnt"),
        F.sum(df.v).over(sliding).alias("ssum"),
        F.min(df.v).over(sliding).alias("smin"),
        F.max(df.v).over(sliding).alias("smax"),
        F.avg(df.v).over(sliding).alias("savg"),
    )
    got = both_engines(out, ["u"])
    exp = pdf.sort_values(["g", "u"]).copy()
    grp = exp.groupby("g")["v"]
    # null-skipping running sum (Spark semantics; pandas cumsum propagates NaN)
    exp["rsum"] = grp.transform(lambda s: s.expanding().sum())
    exp["rcnt"] = grp.expanding().count().reset_index(level=0, drop=True)
    exp["ssum"] = grp.transform(
        lambda s: s.rolling(5, center=True, min_periods=1).sum())
    exp["smin"] = grp.transform(
        lambda s: s.rolling(5, center=True, min_periods=1).min())
    exp["smax"] = grp.transform(
        lambda s: s.rolling(5, center=True, min_periods=1).max())
    exp["savg"] = grp.transform(
        lambda s: s.rolling(5, center=True, min_periods=1).mean())
    exp = exp.sort_values("u").reset_index(drop=True)
    for c in ("rsum", "ssum", "smin", "smax", "savg"):
        assert np.allclose(got[c].fillna(1e18), exp[c].fillna(1e18)), c
    assert (got["rcnt"] == exp["rcnt"].fillna(0).astype(int)).all()


def test_range_frame_peers(sess):
    """Default frame (RANGE unbounded->current) includes peer rows."""
    df, pdf = make_df(sess, with_nulls=False)
    w = Window.partitionBy("g").orderBy("o")  # ties in o => peers
    out = df.select(df.u, df.g, df.o, df.v,
                    F.sum(df.v).over(w).alias("s"))
    got = both_engines(out, ["u"])
    # oracle: for each row, sum of v over rows in same g with o <= o_i
    exp = []
    for _, r in got.iterrows():
        m = pdf[(pdf.g == r.g) & (pdf.o <= r.o)]
        exp.append(m.v.sum())
    assert np.allclose(got["s"], exp)


def test_range_frame_numeric_offsets(sess):
    df, pdf = make_df(sess, with_nulls=False)
    w = Window.partitionBy("g").orderBy("o").rangeBetween(-5, 5)
    out = df.select(df.u, df.g, df.o, df.v,
                    F.sum(df.v).over(w).alias("s"),
                    F.count(df.v).over(w).alias("c"))
    got = both_engines(out, ["u"])
    for _, r in got.sample(60, random_state=0).iterrows():
        m = pdf[(pdf.g == r.g) & (pdf.o >= r.o - 5) & (pdf.o <= r.o + 5)]
        assert np.isclose(r["s"], m.v.sum()), (r.g, r.o)
        assert r["c"] == m.v.count()


def test_range_frame_desc(sess):
    df, pdf = make_df(sess, n=200, with_nulls=False)
    w = Window.partitionBy("g").orderBy(df.o.desc()).rangeBetween(-3, 0)
    out = df.select(df.u, df.g, df.o, df.v,
                    F.count(df.v).over(w).alias("c"))
    got = both_engines(out, ["u"])
    for _, r in got.sample(40, random_state=1).iterrows():
        # desc: "preceding 3" means o in [o_i, o_i + 3]
        m = pdf[(pdf.g == r.g) & (pdf.o <= r.o + 3) & (pdf.o >= r.o)]
        assert r["c"] == m.v.count(), (r.g, r.o)


def test_first_last_nth(sess):
    df, pdf = make_df(sess)
    w = (Window.partitionBy("g").orderBy("u")
         .rowsBetween(Window.unboundedPreceding, Window.unboundedFollowing))
    out = df.select(
        df.u, df.g, df.v,
        F.first(df.v).over(w).alias("f"),
        F.last(df.v).over(w).alias("l"),
        F.first(df.v, ignorenulls=True).over(w).alias("fnn"),
        F.nth_value(df.v, 3).over(w).alias("n3"),
    )
    got = both_engines(out, ["u"])
    exp = pdf.sort_values(["g", "u"])
    for gv, grp in exp.groupby("g"):
        rows = got[got.g == gv]
        seq = grp["v"].tolist()
        assert all(_eq(x, seq[0]) for x in rows["f"])
        assert all(_eq(x, seq[-1]) for x in rows["l"])
        nn = grp["v"].dropna()
        if len(nn):
            assert all(_eq(x, nn.iloc[0]) for x in rows["fnn"])
        n3 = seq[2] if len(seq) >= 3 else None
        assert all(_eq(x, n3) for x in rows["n3"])


def _eq(a, b):
    an = a is None or (isinstance(a, float) and np.isnan(a))
    bn = b is None or (isinstance(b, float) and np.isnan(b))
    if an or bn:
        return an and bn
    return np.isclose(a, b)


def test_no_partition_window(sess):
    df, pdf = make_df(sess, n=100)
    w = Window.orderBy("u")
    out = df.select(df.u, F.row_number().over(w).alias("rn"),
                    F.sum(df.v).over(w).alias("s"))
    got = both_engines(out, ["u"])
    assert (got["rn"] == np.arange(1, 101)).all()
    exp = pdf.sort_values("u")["v"].expanding().sum()
    assert np.allclose(got["s"].fillna(1e18), exp.fillna(1e18).values)


def test_multiple_specs_chain(sess):
    """Two different (partition, order) specs => chained Window nodes."""
    df, pdf = make_df(sess, n=150)
    w1 = Window.partitionBy("g").orderBy("u")
    w2 = Window.orderBy("u")
    out = df.select(df.u, df.g,
                    F.row_number().over(w1).alias("rn_g"),
                    F.row_number().over(w2).alias("rn_all"))
    got = both_engines(out, ["u"])
    assert (got["rn_all"] == np.arange(1, 151)).all()
    for _, grp in got.groupby("g"):
        assert sorted(grp["rn_g"]) == list(range(1, len(grp) + 1))


def test_window_explain_placement(sess):
    df, _ = make_df(sess, n=50)
    w = Window.partitionBy("g").orderBy("u")
    out = df.select(df.u, F.row_number().over(w).alias("rn"))
    report = sess.explain(out)
    assert "TpuWindow" in report


def test_string_spec_resolution_stays_on_device(sess):
    """String-named spec columns must resolve (not leave void attrs that
    silently force host fallback)."""
    df, _ = make_df(sess, n=50, with_nulls=False)
    w = Window.partitionBy("g").orderBy("o").rangeBetween(-5, 5)
    out = df.select(df.u, F.sum(df.v).over(w).alias("s"))
    from spark_rapids_tpu.sql.overrides import TpuOverrides
    meta = TpuOverrides.apply(out._plan, sess._conf)
    def backends(m):
        yield type(m.node).__name__, m.backend, m.reasons
        for c in m.children:
            yield from backends(c)
    for name, be, reasons in backends(meta):
        assert be == "tpu", (name, reasons)


def test_lag_string_with_default(sess):
    t = pa.table({"g": [1, 1, 1, 2, 2], "u": [1, 2, 3, 4, 5],
                  "s": ["aa", "bbbb", "c", "dd", "e"]})
    df = sess.create_dataframe(t)
    w = Window.partitionBy("g").orderBy("u")
    got = df.select(df.u, F.lag(df.s, 1, "zzz").over(w).alias("p")) \
        .collect().to_pandas().sort_values("u")
    assert got["p"].tolist() == ["zzz", "aa", "bbbb", "zzz", "dd"]


def test_range_frame_int64_precision(sess):
    base = 10_000_000_000_000_000  # beyond float64 integer precision
    t = pa.table({"g": [1] * 4, "o": [base, base + 1, base + 2, base + 3],
                  "v": [1.0, 1.0, 1.0, 1.0]})
    df = sess.create_dataframe(t)
    w = Window.partitionBy("g").orderBy("o").rangeBetween(-1, 0)
    got = df.select(df.o, F.count(df.v).over(w).alias("c")) \
        .collect().to_pandas().sort_values("o")
    assert got["c"].tolist() == [1, 2, 2, 2]


def test_ntile_rejects_nonpositive():
    with pytest.raises(ValueError):
        F.ntile(0)


def test_identical_specs_share_one_window_node(sess):
    df, _ = make_df(sess, n=30)
    out = df.select(
        df.u,
        F.row_number().over(Window.partitionBy("g").orderBy("o")).alias("a"),
        F.rank().over(Window.partitionBy("g").orderBy("o")).alias("b"))
    import spark_rapids_tpu.sql.plan as P
    n_windows = 0
    node = out._plan
    stack = [node]
    while stack:
        nd = stack.pop()
        if isinstance(nd, P.Window):
            n_windows += 1
        stack.extend(nd.children)
    assert n_windows == 1


def test_range_frame_mixed_unbounded_numeric(sess):
    """ADVICE r1 (high): integral RANGE frame mixing an unbounded bound with
    a numeric bound must not overflow on the +/-2^63 sentinel."""
    df, pdf = make_df(sess, with_nulls=False)
    w = (Window.partitionBy("g").orderBy("o")
         .rangeBetween(Window.unboundedPreceding, 2))
    out = df.select(df.u, df.g, df.o, df.v,
                    F.sum(df.v).over(w).alias("s"),
                    F.count(df.v).over(w).alias("c"))
    got = both_engines(out, ["u"])
    for _, r in got.sample(40, random_state=2).iterrows():
        m = pdf[(pdf.g == r.g) & (pdf.o <= r.o + 2)]
        assert np.isclose(r["s"], m.v.sum()), (r.g, r.o)
        assert r["c"] == m.v.count()


def test_range_frame_numeric_to_unbounded(sess):
    df, pdf = make_df(sess, n=200, with_nulls=False)
    w = (Window.partitionBy("g").orderBy("o")
         .rangeBetween(-3, Window.unboundedFollowing))
    out = df.select(df.u, df.g, df.o, df.v,
                    F.count(df.v).over(w).alias("c"))
    got = both_engines(out, ["u"])
    for _, r in got.sample(40, random_state=3).iterrows():
        m = pdf[(pdf.g == r.g) & (pdf.o >= r.o - 3)]
        assert r["c"] == m.v.count(), (r.g, r.o)


# --- WindowGroupLimitExec (rank-limit pushdown, Spark 3.5 shim exec) -------

def _wgl_data(sess, n=8000, groups=40):
    import numpy as np
    import pyarrow as pa
    rng = np.random.default_rng(7)
    t = pa.table({"g": rng.integers(0, groups, n), "v": rng.random(n)})
    return sess.create_dataframe(t, num_partitions=4), t.to_pandas()


def test_window_group_limit_planned_and_exact(sess):
    from spark_rapids_tpu.sql.window_api import Window
    df, pdf = _wgl_data(sess)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    q = df.withColumn("r", F.row_number().over(w)).filter(F.col("r") <= 5)
    assert "WindowGroupLimit" in sess.explain(q)
    out = q.collect()
    want = (pdf.sort_values(["g", "v"], ascending=[True, False])
            .groupby("g").head(5))
    assert out.num_rows == len(want)
    got = out.to_pandas().sort_values(["g", "v"]).reset_index(drop=True)
    want = want.sort_values(["g", "v"]).reset_index(drop=True)
    assert (got["g"].values == want["g"].values).all()
    assert abs(got["v"].values - want["v"].values).max() < 1e-12


def test_window_group_limit_rank_ties(sess):
    import pyarrow as pa
    from spark_rapids_tpu.sql.window_api import Window
    t = pa.table({"g": [1, 1, 1, 1, 2, 2],
                  "v": [5.0, 5.0, 4.0, 3.0, 9.0, 9.0]})
    df = sess.create_dataframe(t, num_partitions=2)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    q = df.withColumn("r", F.rank().over(w)).filter(F.col("r") <= 1)
    assert "WindowGroupLimit" in sess.explain(q)
    out = q.collect().to_pandas().sort_values(["g", "v"])
    # rank()<=1 keeps ALL tied-top rows
    assert out["v"].tolist() == [5.0, 5.0, 9.0, 9.0]


def test_window_group_limit_not_planned_without_rank(sess):
    from spark_rapids_tpu.sql.window_api import Window
    df, _ = _wgl_data(sess)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    # sum() over a window is not a rank function: no pushdown
    q = df.withColumn("s", F.sum(F.col("v")).over(w)).filter(
        F.col("s") <= 2.0)
    assert "WindowGroupLimit" not in sess.explain(q)


def test_window_group_limit_strict_less(sess):
    from spark_rapids_tpu.sql.window_api import Window
    df, pdf = _wgl_data(sess)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    q = df.withColumn("r", F.row_number().over(w)).filter(F.col("r") < 3)
    assert "WindowGroupLimit" in sess.explain(q)
    want = (pdf.sort_values(["g", "v"], ascending=[True, False])
            .groupby("g").head(2))
    assert q.collect().num_rows == len(want)


def test_window_group_limit_not_planned_with_mixed_functions(sess):
    """lead()/aggregates sharing the spec forbid the pushdown (they'd see
    truncated input)."""
    from spark_rapids_tpu.sql.window_api import Window
    df, _ = _wgl_data(sess)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    q = (df.withColumn("r", F.row_number().over(w))
           .withColumn("nxt", F.lead(F.col("v")).over(w))
           .filter(F.col("r") <= 3))
    assert "WindowGroupLimit" not in sess.explain(q)


def test_window_group_limit_does_not_leak_to_unfiltered_plan(sess):
    """Planning the filtered query must not mutate the shared logical
    Window node: collecting the UNfiltered base afterwards returns all
    rows."""
    from spark_rapids_tpu.sql.window_api import Window
    df, pdf = _wgl_data(sess, n=2000, groups=10)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    base = df.withColumn("r", F.row_number().over(w))
    top = base.filter(F.col("r") <= 5)
    assert "WindowGroupLimit" in sess.explain(top)
    assert top.collect().num_rows == 50
    assert base.collect().num_rows == len(pdf)  # no silent row loss
    assert "WindowGroupLimit" not in sess.explain(base)


def test_window_group_limit_shared_node_with_unfiltered_branch(sess):
    """A Window consumed by BOTH a rank-filtered branch and an unfiltered
    branch in ONE plan must not get the pushdown."""
    from spark_rapids_tpu.sql.window_api import Window
    df, pdf = _wgl_data(sess, n=1000, groups=5)
    w = Window.partitionBy("g").orderBy(F.col("v").desc())
    base = df.withColumn("r", F.row_number().over(w))
    top = base.filter(F.col("r") <= 2)
    both = top.union(base)
    assert "WindowGroupLimit" not in sess.explain(both)
    assert both.collect().num_rows == 10 + len(pdf)


# --- key-batched out-of-core windows (GpuKeyBatchingIterator analog) -------

def test_window_key_batched_matches_in_core(sess):
    """Tiny chunk target forces many key-complete chunks; results must be
    identical to the one-batch path."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.sql.window_api import Window
    rng = np.random.default_rng(13)
    n = 20_000
    t = pa.table({"g": np.sort(rng.integers(0, 300, n)),
                  "v": rng.random(n)})
    w = Window.partitionBy("g").orderBy("v")

    def q(s):
        df = s.create_dataframe(t, num_partitions=1)
        return (df.withColumn("r", F.row_number().over(w))
                .withColumn("s", F.sum(F.col("v")).over(w))
                .orderBy("g", "v").collect().to_pandas())
    small = srt.session(**{"spark.rapids.sql.window.batchTargetRows": 500})
    try:
        got = q(small)
        assert small.last_query_metrics.get("windowKeyBatches", 0) > 5
        big = srt.session(
            **{"spark.rapids.sql.window.batchTargetRows": 1 << 22})
        want = q(big)
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
    assert (got["r"].values == want["r"].values).all()
    assert abs(got["s"].values - want["s"].values).max() < 1e-9


def test_window_key_batched_single_giant_partition(sess):
    """One partition larger than the target cannot be cut: the chunk
    grows to hold it and results stay exact."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.sql.window_api import Window
    n = 3_000
    t = pa.table({"g": [1] * n, "v": list(range(n))})
    w = Window.partitionBy("g").orderBy("v")
    s = srt.session(**{"spark.rapids.sql.window.batchTargetRows": 100})
    try:
        df = s.create_dataframe(t, num_partitions=1)
        out = (df.withColumn("r", F.row_number().over(w))
               .orderBy("v").collect())
        assert out["r"].to_pylist() == list(range(1, n + 1))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})


def test_window_key_batched_with_oom_injection(sess):
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.sql.window_api import Window
    rng = np.random.default_rng(14)
    n = 5_000
    t = pa.table({"g": np.sort(rng.integers(0, 50, n)),
                  "v": rng.random(n)})
    w = Window.partitionBy("g").orderBy("v")
    s = srt.session(**{
        "spark.rapids.sql.window.batchTargetRows": 400,
        "spark.rapids.sql.test.injectRetryOOM": 2})
    try:
        df = s.create_dataframe(t, num_partitions=1)
        out = (df.withColumn("r", F.row_number().over(w)).collect())
        assert out.num_rows == n
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
