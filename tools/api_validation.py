"""API-drift validation — the analog of the reference's ``api_validation``
module (``ApiValidation.scala``: compares Gpu exec constructor signatures
against each Spark version's APIs so a shim mismatch is caught at build
time, not at runtime deep inside a query).

Two validations, both runnable standalone and from CI/tests:

1. **Engine contract** — every physical exec's constructor signature and
   every registered expression class is snapshotted into
   ``tools/generated_files/api_contract.json``; a later run against the
   contract reports removed/renamed classes and incompatible constructor
   changes (the drift the reference catches across its 14 shims).
2. **jax surface** — every jax API the shims/engine lean on is probed
   against the RUNNING jax version (the TPU build's version axis, SURVEY
   §2.11 TPU note), so a jaxlib upgrade that moves an entry point fails
   loudly here.

Usage:
    python tools/api_validation.py generate   # write the contract
    python tools/api_validation.py check      # validate against it
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import sys
from typing import Dict, List

CONTRACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "generated_files", "api_contract.json")

_EXEC_MODULES = [
    "spark_rapids_tpu.sql.physical.basic",
    "spark_rapids_tpu.sql.physical.aggregate",
    "spark_rapids_tpu.sql.physical.join",
    "spark_rapids_tpu.sql.physical.sortlimit",
    "spark_rapids_tpu.sql.physical.window",
    "spark_rapids_tpu.sql.physical.exchange",
    "spark_rapids_tpu.sql.physical.transitions",
    "spark_rapids_tpu.sql.physical.generate",
    "spark_rapids_tpu.sql.physical.python_execs",
    "spark_rapids_tpu.sql.physical.fusion",
    "spark_rapids_tpu.sql.physical.dpp",
    "spark_rapids_tpu.io_.exec",
]

#: jax entry points the engine/shims rely on (probed, not imported lazily,
#: so a jax upgrade that moves one fails HERE with a clear message)
_JAX_SURFACE = [
    "jax.jit", "jax.device_get", "jax.device_put", "jax.tree.map",
    "jax.lax.sort", "jax.lax.while_loop", "jax.lax.scan",
    "jax.lax.associative_scan", "jax.lax.cond",
    "jax.sharding.Mesh", "jax.sharding.NamedSharding",
    "jax.sharding.PartitionSpec", "jax.experimental.shard_map.shard_map",
    "jax.block_until_ready", "jax.profiler.TraceAnnotation",
    "jax.nn.one_hot", "jax.numpy.argsort", "jax.numpy.cumsum",
]


def _exec_signatures() -> Dict[str, List[str]]:
    from spark_rapids_tpu.sql.physical.base import PhysicalPlan
    out: Dict[str, List[str]] = {}
    for mod_name in _EXEC_MODULES:
        mod = importlib.import_module(mod_name)
        for name, cls in vars(mod).items():
            if (inspect.isclass(cls) and issubclass(cls, PhysicalPlan)
                    and cls is not PhysicalPlan
                    and cls.__module__ == mod_name):
                try:
                    params = [p.name for p in
                              inspect.signature(cls.__init__).parameters
                              .values()][1:]  # drop self
                except (TypeError, ValueError):
                    params = []
                out[f"{mod_name}.{name}"] = params
    return out


def _expression_names() -> List[str]:
    from spark_rapids_tpu.sql.expressions.registry import EXPRESSION_REGISTRY
    return sorted(EXPRESSION_REGISTRY)


def _probe_jax_surface() -> List[str]:
    missing = []
    for path in _JAX_SURFACE:
        mod_path, attr = path.rsplit(".", 1)
        try:
            obj = importlib.import_module(mod_path)
        except ImportError:
            # dotted attribute chains (jax.tree.map)
            parts = path.split(".")
            try:
                obj = importlib.import_module(parts[0])
                for p in parts[1:-1]:
                    obj = getattr(obj, p)
                attr = parts[-1]
            except (ImportError, AttributeError):
                missing.append(path)
                continue
        if not hasattr(obj, attr):
            missing.append(path)
    return missing


def generate() -> dict:
    contract = {
        "execs": _exec_signatures(),
        "expressions": _expression_names(),
    }
    os.makedirs(os.path.dirname(CONTRACT), exist_ok=True)
    with open(CONTRACT, "w") as fh:
        json.dump(contract, fh, indent=1, sort_keys=True)
    return contract


def check() -> List[str]:
    """Returns a list of drift findings (empty = clean)."""
    problems: List[str] = []
    missing_jax = _probe_jax_surface()
    for p in missing_jax:
        problems.append(f"jax surface: {p} is gone in the running jax "
                        f"(add a shim provider)")
    if not os.path.exists(CONTRACT):
        problems.append(f"contract file missing: {CONTRACT} "
                        f"(run `generate` first)")
        return problems
    with open(CONTRACT) as fh:
        contract = json.load(fh)
    now_execs = _exec_signatures()
    for name, params in contract["execs"].items():
        if name not in now_execs:
            problems.append(f"exec removed/renamed: {name}")
        else:
            got = now_execs[name]
            # removing or reordering existing positional params breaks
            # callers; appending new defaulted params is fine
            if got[:len(params)] != params:
                problems.append(
                    f"exec constructor changed incompatibly: {name} "
                    f"{params} -> {got}")
    now_exprs = set(_expression_names())
    for e in contract["expressions"]:
        if e not in now_exprs:
            problems.append(f"expression unregistered: {e}")
    return problems


def main() -> int:
    cmd = sys.argv[1] if len(sys.argv) > 1 else "check"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    jax.config.update("jax_platforms", "cpu")
    if cmd == "generate":
        c = generate()
        print(f"wrote {CONTRACT}: {len(c['execs'])} execs, "
              f"{len(c['expressions'])} expressions")
        return 0
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}")
    print(f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
