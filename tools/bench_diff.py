#!/usr/bin/env python3
"""Bench regression sentinel — diff two BENCH artifacts with thresholded
verdicts.

Compares the primary rows/s metric, per-shape extra metrics
(join/window/sort/whole-stage/encoded), trace summaries (sync counts/ms,
compile ms, bytes on the wire), stage dispatch counts and wire bytes,
and prints one verdict line per comparable metric:

    OK        within the threshold band
    IMPROVED  better by more than the threshold
    REGRESSED worse by more than the threshold
    ONLY-A / ONLY-B   metric present in one artifact only

Direction matters: rows/s, vs_baseline and GB/s improve UP; sync counts,
compile ms, dispatches and bytes-on-wire improve DOWN.

Evidence gating (ROADMAP item 5): an artifact is ``live`` (a real device
measurement from this round), ``stale-replay`` (a replayed tunnel-window
capture — bench.py stamps ``evidence``/``captured_at``) or
``cpu-fallback``.  Comparing live vs stale-replay is refused without
``--allow-stale``: a stale replay masquerading as the "before" side
manufactures phantom regressions/improvements.

Ledger mode (the perf sentry's evidence ledger,
.bench_capture/ledger.jsonl, srt-ledger/1): ``--ledger <path>`` resolves
the comparison baseline (side A) automatically as the artifact of the
NEWEST ``evidence: live`` ledger entry — a stale replay never becomes
the baseline no matter how recently it was appended.  With no live
entry the diff is REFUSED (exit 2); ``--allow-stale`` degrades the
resolution to the newest entry of any evidence class, with the usual
cross-evidence warning.

Usage:
  python tools/bench_diff.py A.json B.json [--threshold 0.10]
         [--allow-stale] [--fail-on-regress] [--json]
  python tools/bench_diff.py --ledger LEDGER.jsonl B.json [flags...]

Accepts driver round artifacts ({"parsed": {...}}), raw bench stdout
(last JSON line wins), or a bare result object.  Exit codes: 0 ok,
1 usage/parse error, 2 evidence mismatch / baseline resolution refused,
3 regressions found (only with --fail-on-regress).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Tuple

#: metric-name fragments whose value improves DOWNWARD.  Serving-tier
#: latency records (docs/serving.md) join here: p50/p99 latency and
#: admission wait improve DOWN while qps improves UP (the default), so
#: "QPS up is IMPROVED, p99 up is REGRESSED" falls out of the fragments.
_LOWER_BETTER = ("sync_count", "sync_ms", "compile_ms", "compile_count",
                 "bytes_on_wire", "dispatches", "spill_ms", "sem_wait_ms",
                 "dropped_events", "h2d_bytes", "d2h_bytes", "seconds",
                 "_us", "p50", "p95", "p99", "latency", "wait_ms",
                 "wall_s",
                 # query-lifecycle records (docs/robustness.md): cancel
                 # drain latency, deadline overshoot and quarantine
                 # counts all improve DOWN
                 "cancel_latency", "overshoot", "quarantine_count",
                 # fault_recovery records (testing/chaos_cluster.py):
                 # detection / recompute / query latencies improve DOWN
                 "detection_ms", "recompute_ms", "query_ms")
#: keys that are identifiers/context, never diffed
_SKIP = ("rows", "chips", "queries", "probe_attempts", "budget_ms",
         "elapsed_ms", "partial_banked_at", "pipeline_host_cores",
         "workload_queries", "parallelism", "tenants",
         "distinct_queries", "serving_rows", "deadline_ms",
         "cancels_measured", "degraded_queries")


def load_artifact(path: str) -> Dict[str, Any]:
    """Load a bench result from a driver artifact, raw stdout capture, or
    bare result JSON."""
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            if "parsed" in doc and isinstance(doc["parsed"], dict):
                return doc["parsed"]
            if "metric" in doc or "value" in doc:
                return doc
    except ValueError:
        pass
    # raw stdout: last JSON line carrying a final result wins
    best = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and ("metric" in rec or "value" in rec):
            best = rec
    if best is None:
        raise ValueError(f"{path}: no bench result record found")
    return best


def evidence_of(rec: Dict[str, Any]) -> str:
    """The artifact's evidence class; derives it for artifacts banked
    before bench.py stamped ``evidence`` explicitly."""
    ev = rec.get("evidence")
    if ev:
        return str(ev)
    if "captured_at" in rec:
        return "stale-replay"
    if rec.get("platform") == "cpu" or rec.get("platform") is None:
        return "cpu-fallback"
    return "live"


def read_ledger(path: str) -> List[Dict[str, Any]]:
    """Parse an srt-ledger/1 evidence ledger (append-only JSONL),
    skipping torn or foreign lines — mirrors
    observability/sentry.EvidenceLedger.entries() without importing the
    package (this tool stays dependency-free)."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn line (crash mid-append)
            if isinstance(rec, dict) and rec.get("schema") == "srt-ledger/1":
                out.append(rec)
    return out


def resolve_baseline(entries: List[Dict[str, Any]],
                     allow_stale: bool = False) -> Optional[str]:
    """Baseline artifact path from ledger entries: the newest
    ``evidence: live`` entry carrying an artifact path.  ``allow_stale``
    falls back to the newest entry of ANY evidence class — the evidence
    gate in run() then prints the cross-evidence warning."""
    for rec in reversed(entries):
        if rec.get("evidence") == "live" and rec.get("artifact"):
            return str(rec["artifact"])
    if allow_stale:
        for rec in reversed(entries):
            if rec.get("artifact"):
                return str(rec["artifact"])
    return None


def _flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a nested dict, dotted-path keyed; skips
    identifier keys and underscore-private keys."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k.startswith("_") or k in _SKIP or k.endswith("_rows"):
                continue  # sizes are context, not rates
            out.update(_flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix[:-1]] = float(obj)
    return out


def comparable_metrics(rec: Dict[str, Any]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(rec.get("value"), (int, float)) and rec.get("value"):
        out[str(rec.get("metric", "value"))] = float(rec["value"])
    for k in ("vs_baseline", "gb_per_s_per_chip", "trace_overhead",
              "chaos_overhead", "sync_rtt_ms"):
        v = rec.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    ts = rec.get("trace_summary")
    if isinstance(ts, dict):
        out.update(_flatten(ts, "trace_summary."))
    em = rec.get("extra_metrics")
    if isinstance(em, dict):
        out.update(_flatten(em, ""))
    return out


def lower_is_better(name: str) -> bool:
    return any(f in name for f in _LOWER_BETTER)


def diff(a: Dict[str, float], b: Dict[str, float], threshold: float
         ) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(a) | set(b)):
        va, vb = a.get(name), b.get(name)
        if va is None or vb is None:
            rows.append({"metric": name, "a": va, "b": vb,
                         "verdict": "ONLY-B" if va is None else "ONLY-A"})
            continue
        if va == 0:
            ratio = None
            verdict = "OK" if vb == 0 else "CHANGED"
        else:
            ratio = vb / va
            rel = ratio - 1.0
            if lower_is_better(name):
                rel = -rel
            if rel >= threshold:
                verdict = "IMPROVED"
            elif rel <= -threshold:
                verdict = "REGRESSED"
            else:
                verdict = "OK"
        rows.append({"metric": name, "a": va, "b": vb,
                     "ratio": round(ratio, 4) if ratio is not None
                     else None, "verdict": verdict})
    return rows


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}"


def run(path_a: str, path_b: str, threshold: float, allow_stale: bool,
        as_json: bool) -> Tuple[int, List[Dict[str, Any]]]:
    ra, rb = load_artifact(path_a), load_artifact(path_b)
    ea, eb = evidence_of(ra), evidence_of(rb)
    if ea != eb and not allow_stale:
        print(f"REFUSED: evidence mismatch — {path_a} is '{ea}', "
              f"{path_b} is '{eb}'.  A stale replay or CPU fallback is "
              f"not comparable to a live device measurement; rerun with "
              f"--allow-stale to force.", file=sys.stderr)
        return 2, []
    rows = diff(comparable_metrics(ra), comparable_metrics(rb), threshold)
    regressed = [r for r in rows if r["verdict"] == "REGRESSED"]
    improved = [r for r in rows if r["verdict"] == "IMPROVED"]
    header = {"a": {"path": path_a, "evidence": ea,
                    "note": ra.get("note", "")[:120]},
              "b": {"path": path_b, "evidence": eb,
                    "note": rb.get("note", "")[:120]},
              "threshold": threshold,
              "regressed": len(regressed), "improved": len(improved)}
    if as_json:
        print(json.dumps({"header": header, "rows": rows}, indent=1))
    else:
        print(f"A: {path_a}  [evidence: {ea}]")
        print(f"B: {path_b}  [evidence: {eb}]")
        if ea != eb:
            print("WARNING: comparing across evidence classes "
                  "(--allow-stale)")
        print(f"threshold: ±{threshold:.0%}\n")
        w = max((len(r["metric"]) for r in rows), default=10)
        print(f"{'metric':<{w}} {'A':>14} {'B':>14} {'B/A':>8}  verdict")
        for r in rows:
            ratio = "-" if r.get("ratio") is None else f"{r['ratio']:.3f}"
            print(f"{r['metric']:<{w}} {_fmt(r['a']):>14} "
                  f"{_fmt(r['b']):>14} {ratio:>8}  {r['verdict']}")
        print(f"\nSUMMARY: {len(improved)} improved, {len(regressed)} "
              f"regressed, {len(rows) - len(improved) - len(regressed)} "
              f"other")
        for r in regressed:
            print(f"  REGRESSED {r['metric']}: {_fmt(r['a'])} -> "
                  f"{_fmt(r['b'])} ({r['ratio']:.3f}x)")
    return (0, rows)


def main(argv: List[str]) -> int:
    if len(argv) < 2 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 1
    threshold = 0.10
    allow_stale = "--allow-stale" in argv
    fail_on_regress = "--fail-on-regress" in argv
    as_json = "--json" in argv
    argv = [a for a in argv
            if a not in ("--allow-stale", "--fail-on-regress", "--json")]
    if "--threshold" in argv:
        i = argv.index("--threshold")
        threshold = float(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    if "--ledger" in argv:
        i = argv.index("--ledger")
        ledger_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
        if len(argv) != 1:
            print(__doc__)
            return 1
        try:
            entries = read_ledger(ledger_path)
        except OSError as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 1
        baseline = resolve_baseline(entries, allow_stale=allow_stale)
        if baseline is None:
            print(f"REFUSED: no 'evidence: live' entry with an artifact "
                  f"in ledger {ledger_path} ({len(entries)} entries) — "
                  f"there is no live baseline to diff against.  Capture "
                  f"a live window first, or rerun with --allow-stale to "
                  f"fall back to the newest entry of any evidence "
                  f"class.", file=sys.stderr)
            return 2
        argv = [baseline] + argv
    if len(argv) != 2:
        print(__doc__)
        return 1
    try:
        rc, rows = run(argv[0], argv[1], threshold, allow_stale, as_json)
    except (OSError, ValueError) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1
    if rc:
        return rc
    if fail_on_regress and any(r["verdict"] == "REGRESSED" for r in rows):
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
