#!/usr/bin/env python3
"""Process-kill chaos harness CLI — the pod-scale fault-domain proof.

Spawns a real N-process shuffle topology (driver registry + N executor
subprocesses on the TCP plane) and runs the seeded fault scenarios from
``spark_rapids_tpu.testing.chaos_cluster``:

  sigkill     SIGKILL an executor mid-query: retry -> lineage recompute,
              failure-detector dead-declaration, bit-identical digest.
  zombie      SIGSTOP past dead-declaration + replacement registration
              (epoch bump), then SIGCONT: every stale-epoch response the
              zombie serves must be REFUSED (fencing proof) while the
              result stays bit-identical.
  partition   frozen peer (asymmetric partition): post-declaration
              fetches take the dead-skip fast path straight to
              recompute.

Writes ``report.json`` (with the ``fault_recovery`` latency record that
tools/bench_diff.py can diff) plus per-process trace event logs suitable
for tools/trace_merge.py + check_trace --require-cat fault.

Usage:
  python tools/chaos_cluster.py [--procs 3] [--seed 7] [--rows 512]
         [--scenario sigkill|zombie|partition|all] [--out DIR] [--json]

Exit codes: 0 every scenario bit-identical and fenced, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="process-kill chaos harness for the shuffle fault "
                    "domain")
    p.add_argument("--procs", type=int, default=3,
                   help="executor process count (>= 2; default 3)")
    p.add_argument("--seed", type=int, default=7,
                   help="seed for data AND fault points (default 7)")
    p.add_argument("--rows", type=int, default=512,
                   help="rows per map task (default 512)")
    p.add_argument("--scenario", action="append",
                   choices=["sigkill", "zombie", "partition", "all"],
                   help="fault scenario to run; repeatable (default all)")
    p.add_argument("--out", default="",
                   help="output dir for report.json + event logs "
                        "(default: a fresh temp dir)")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON instead of the "
                        "human summary")
    return p


def main(argv) -> int:
    args = build_arg_parser().parse_args(argv)
    # runnable from anywhere: the engine lives one level up from tools/
    # (the leak_sentinel.py pattern — the package is not pip-installed)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    # the child executors import the package by name too
    os.environ["PYTHONPATH"] = repo + os.pathsep + os.environ.get(
        "PYTHONPATH", "")
    plat = os.environ.get("SRT_CHAOS_PLATFORM", "cpu")
    if plat == "cpu":
        from spark_rapids_tpu import pin_host_platform
        pin_host_platform()
    from spark_rapids_tpu.testing.chaos_cluster import SCENARIOS, run_suite

    out = args.out or tempfile.mkdtemp(prefix="srt-chaos-cluster-")
    os.makedirs(out, exist_ok=True)
    selected = args.scenario or ["all"]
    names = (list(SCENARIOS) if "all" in selected
             else [s for s in SCENARIOS if s in selected])
    report = run_suite(names, nprocs=args.procs, seed=args.seed,
                       rows=args.rows, out_dir=out)
    report["out_dir"] = out
    with open(os.path.join(out, "report.json"), "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for r in report["scenarios"]:
            bits = [f"{r['scenario']:<9}",
                    "bit-identical" if r["ok"] else "PARITY BROKEN"]
            for k in ("detection_ms", "recompute_ms",
                      "degraded_query_ms", "stale_epochs_refused",
                      "blocks_recomputed", "dead_failovers"):
                if k in r:
                    bits.append(f"{k}={r[k]}")
            print("  ".join(bits))
        print(f"report: {os.path.join(out, 'report.json')}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
