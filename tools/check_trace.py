#!/usr/bin/env python3
"""Validate an exported Chrome trace-event JSON file against the
trace-event schema subset the tracer emits (observability/export.py):
every event must carry ph/ts/pid/tid/name; "X" complete events must
carry a non-negative dur.  Used by ci/run_ci.sh after the traced-query
step and by tests/test_tracer.py.

Usage: python tools/check_trace.py [<trace.json> ...] [--min-events N]
           [--require-cat CAT] [--require-arg KEY]
           [--prometheus FILE] [--prometheus-label KEY]
           [--doctor FILE] [--flow FILE] [--endpoint URL]
``--require-cat`` additionally fails unless at least one span event
carries that category (e.g. ``fault`` for chaos-soak traces).
``--require-arg`` fails unless at least one span event carries that
args key (e.g. ``tenant`` for serving-engine traces).
``--prometheus-label`` fails unless at least one Prometheus sample
carries that label key (e.g. ``tenant`` for serving metrics).
``--prometheus`` validates a metrics-registry export against the
Prometheus exposition contract (typed series, cumulative histogram
buckets ending at +Inf, consistent _sum/_count).
``--doctor`` validates a doctor diagnosis JSON against the
srt-doctor/1 schema (known verdict, ranked entries with
category/ms/share/evidence).
``--flow`` validates a merged trace (tools/trace_merge.py output):
every flow id must have both an "s" start and an "f" finish, each
anchored inside a real span on the same pid/tid, and every pid with
spans must carry process_name metadata.
``--endpoint`` scrapes a live telemetry server URL
(observability/server.py) and validates the response body: a
/metrics-style body is held to the Prometheus exposition contract; a
JSON body carrying ``schema: srt-sentry/1`` (the /sentry route) is held
to the sentry status contract (known phase, probe telemetry with
classified outcomes, ledger tail of valid srt-ledger/1 entries).
Exit 0 when every requested check passes, 1 otherwise.
"""

import json
import sys

REQUIRED = ("ph", "ts", "pid", "tid", "name")
KNOWN_PH = ("X", "C", "i", "M", "B", "E", "s", "t", "f")

#: categories the tracer emits today (observability/tracer.py
#: CATEGORIES); unknown categories stay opaque — listed for reference
#: and for --require-cat hints, not validated
KNOWN_CATS = ("op", "kernel_compile", "sync", "h2d", "d2h", "spill",
              "shuffle", "sem_wait", "fault", "queue", "encode", "stage",
              "admission", "cancel", "fatal")


def check(path: str, min_events: int = 1, require_cat: str = "",
          require_arg: str = ""):
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list")
    spans = 0
    cats = set()
    arg_keys = set()
    for i, ev in enumerate(events):
        for field in REQUIRED:
            if field not in ev:
                raise ValueError(f"event {i} missing required field "
                                 f"{field!r}: {ev}")
        if ev["ph"] not in KNOWN_PH:
            raise ValueError(f"event {i} has unknown ph {ev['ph']!r}")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"event {i} ts is not numeric: {ev['ts']!r}")
        if ev["ph"] == "X":
            if "dur" not in ev or not isinstance(ev["dur"], (int, float)) \
                    or ev["dur"] < 0:
                raise ValueError(f"event {i} 'X' span needs dur >= 0: {ev}")
            spans += 1
            cats.add(ev.get("cat", ""))
            for k in (ev.get("args") or {}):
                arg_keys.add(k)
    if spans < min_events:
        raise ValueError(f"expected at least {min_events} span event(s), "
                         f"found {spans}")
    if require_cat and require_cat not in cats:
        raise ValueError(
            f"no span event with category {require_cat!r} "
            f"(found: {sorted(c for c in cats if c)})")
    if require_arg and require_arg not in arg_keys:
        raise ValueError(
            f"no span event carrying args[{require_arg!r}] "
            f"(found arg keys: {sorted(arg_keys)})")
    return spans, sorted(c for c in cats if c)


#: the doctor's verdict taxonomy (observability/doctor.py VERDICTS)
DOCTOR_VERDICTS = ("sync-bound", "compile-bound", "h2d-d2h-bound",
                   "dispatch-bound", "sem_wait-bound", "spill-bound",
                   "shuffle-bound", "admission-bound", "slo-burn",
                   "no-bottleneck")


def check_flow(path: str, min_flows: int = 1):
    """Validate cross-process flow stitching in a merged trace: every
    flow id pairs an "s" with an "f", both landing inside a span on the
    same pid/tid, and every pid that has spans is named via "M"
    process_name metadata."""
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    spans_by_track = {}
    named_pids = set()
    span_pids = set()
    flows = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph == "X":
            spans_by_track.setdefault(
                (ev["pid"], ev["tid"]), []).append(
                (float(ev["ts"]), float(ev.get("dur", 0.0))))
            span_pids.add(ev["pid"])
        elif ph == "M" and ev.get("name") == "process_name":
            named_pids.add(ev["pid"])
        elif ph in ("s", "t", "f"):
            if "id" not in ev:
                raise ValueError(f"event {i} flow event missing 'id'")
            flows.setdefault(ev["id"], {})[ph] = ev
    if len(flows) < min_flows:
        raise ValueError(f"expected at least {min_flows} flow id(s), "
                         f"found {len(flows)}")
    for fid, phases in flows.items():
        for need in ("s", "f"):
            if need not in phases:
                raise ValueError(f"flow {fid}: missing {need!r} phase "
                                 f"(has {sorted(phases)})")
        if phases["s"].get("name") != phases["f"].get("name") \
                or phases["s"].get("cat") != phases["f"].get("cat"):
            raise ValueError(f"flow {fid}: s/f name or cat mismatch")
        for ph, ev in phases.items():
            ts = float(ev["ts"])
            track = spans_by_track.get((ev["pid"], ev["tid"]), [])
            if not any(t0 - 1e-6 <= ts <= t0 + dur + 1e-6
                       for t0, dur in track):
                raise ValueError(
                    f"flow {fid} {ph!r} at ts={ts} not inside any span "
                    f"on pid={ev['pid']} tid={ev['tid']}")
    cross = sum(1 for p in flows.values()
                if p["s"]["pid"] != p["f"]["pid"])
    for pid in span_pids:
        if pid not in named_pids:
            raise ValueError(f"pid {pid} has spans but no process_name "
                             f"metadata")
    return len(flows), cross, len(span_pids)


def check_prometheus(path: str, require_label: str = ""):
    """Validate a Prometheus exposition FILE (see _check_prom_lines)."""
    with open(path) as fh:
        return _check_prom_lines(fh, require_label)


#: perf-sentry lifecycle phases (observability/sentry.py PHASES) plus
#: the "none" payload an active-sentry-free process serves
SENTRY_PHASES = ("idle", "probe", "bench", "diff", "ledger", "stopped",
                 "none")
SENTRY_PROBE_OUTCOMES = ("ok", "degraded", "timeout", "refused",
                         "wedged")


def check_sentry(doc) -> str:
    """Validate a /sentry route payload (srt-sentry/1 schema)."""
    if not isinstance(doc, dict):
        raise ValueError("sentry payload is not a JSON object")
    if doc.get("schema") != "srt-sentry/1":
        raise ValueError(f"schema is {doc.get('schema')!r}, "
                         f"expected 'srt-sentry/1'")
    phase = doc.get("phase")
    if phase not in SENTRY_PHASES:
        raise ValueError(f"unknown phase {phase!r}")
    ledger = doc.get("ledger")
    if not isinstance(ledger, dict) or not ledger.get("path"):
        raise ValueError("ledger block missing or without a path")
    tail = ledger.get("tail", [])
    if not isinstance(tail, list):
        raise ValueError("ledger tail is not a list")
    for i, rec in enumerate(tail):
        if not isinstance(rec, dict) \
                or rec.get("schema") != "srt-ledger/1":
            raise ValueError(f"ledger tail[{i}] is not a valid "
                             f"srt-ledger/1 record: {rec!r}")
    if "last_live_age_s" not in doc:
        raise ValueError("missing last_live_age_s")
    if phase != "none":
        probe = doc.get("probe")
        if not isinstance(probe, dict):
            raise ValueError("probe block missing")
        last = probe.get("last")
        if last is not None and last.get("outcome") \
                not in SENTRY_PROBE_OUTCOMES:
            raise ValueError(f"unknown probe outcome "
                             f"{last.get('outcome')!r}")
    return (f"sentry phase {phase}, {len(tail)} ledger tail entr"
            f"{'y' if len(tail) == 1 else 'ies'}, "
            f"last_live_age_s={doc.get('last_live_age_s')}")


def check_endpoint(url: str, require_label: str = "") -> str:
    """Scrape a live telemetry URL and validate the response body:
    Prometheus exposition contract for /metrics-style bodies, the
    srt-sentry/1 status contract for the /sentry route (auto-detected
    from the payload schema)."""
    import urllib.request
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = resp.read().decode("utf-8", "replace")
    if body.lstrip().startswith("{"):
        try:
            doc = json.loads(body)
        except ValueError:
            doc = None
        if isinstance(doc, dict) and doc.get("schema") == "srt-sentry/1":
            return check_sentry(doc)
        if doc is not None:
            schema = (doc.get("schema") if isinstance(doc, dict)
                      else type(doc).__name__)
            raise ValueError("JSON endpoint body with unrecognized "
                             f"schema {schema!r}")
    n, fams = _check_prom_lines(body.splitlines(), require_label)
    return f"{n} samples, {len(fams)} families"


def _check_prom_lines(lines, require_label: str = ""):
    """Validate Prometheus exposition text: every sample belongs to a
    # TYPE-declared family; histogram buckets are cumulative and end at
    +Inf with a count matching _count."""
    import re
    types = {}
    samples = []
    for ln, line in enumerate(lines, 1):
        line = line.rstrip("\n")
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split()
            if typ not in ("counter", "gauge", "histogram"):
                raise ValueError(f"line {ln}: unknown type {typ!r}")
            types[name] = typ
            continue
        if line.startswith("#"):
            continue
        m = re.match(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})? "
                     r"([0-9.eE+-]+|\+Inf|NaN)$", line)
        if not m:
            raise ValueError(f"line {ln}: malformed sample: {line!r}")
        samples.append((m.group(1), m.group(2) or "", m.group(3)))
    if not samples:
        raise ValueError("no samples")
    if require_label and not any(
            f'{require_label}="' in labels for _n, labels, _v in samples):
        raise ValueError(f"no sample carries label {require_label!r}")
    fams = set(types)
    buckets = {}
    for name, labels, value in samples:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in fams:
                base = name[:-len(suffix)]
        if base not in fams:
            raise ValueError(f"sample {name!r} has no # TYPE declaration")
        if name.endswith("_bucket") and types.get(base) == "histogram":
            series = labels.replace('le="', "\0").split("\0")[0]
            buckets.setdefault((base, series), []).append(
                (labels, float("inf") if "+Inf" in labels
                 else None, int(float(value))))
    for (base, _), rows in buckets.items():
        counts = [v for _, _, v in rows]
        if counts != sorted(counts):
            raise ValueError(f"{base}: bucket counts not cumulative")
        if not any(le == float("inf") for _, le, _ in rows):
            raise ValueError(f"{base}: histogram missing +Inf bucket")
    return len(samples), sorted(types)


def check_doctor(path: str):
    """Validate a doctor diagnosis JSON (srt-doctor/1 schema)."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != "srt-doctor/1":
        raise ValueError(f"schema is {doc.get('schema')!r}, "
                         f"expected 'srt-doctor/1'")
    if doc.get("verdict") not in DOCTOR_VERDICTS:
        raise ValueError(f"unknown verdict {doc.get('verdict')!r}")
    ranked = doc.get("ranked")
    if not isinstance(ranked, list):
        raise ValueError("ranked is not a list")
    if doc["verdict"] != "no-bottleneck" and not ranked:
        raise ValueError("non-trivial verdict with empty ranked list")
    last_ms = float("inf")
    for i, e in enumerate(ranked):
        for field in ("category", "ms", "count", "share", "evidence"):
            if field not in e:
                raise ValueError(f"ranked[{i}] missing {field!r}: {e}")
        if e["category"] not in DOCTOR_VERDICTS:
            raise ValueError(f"ranked[{i}] unknown category "
                             f"{e['category']!r}")
        if not 0.0 <= e["share"] <= 1.0:
            raise ValueError(f"ranked[{i}] share out of range: "
                             f"{e['share']}")
        if e["ms"] > last_ms + 1e-9:
            raise ValueError("ranked list not sorted by ms desc")
        last_ms = e["ms"]
    if ranked and doc["verdict"] != ranked[0]["category"]:
        raise ValueError("verdict != top ranked category")
    if not isinstance(doc.get("trace_truncated"), bool):
        raise ValueError("trace_truncated missing or not bool")
    return doc["verdict"], len(ranked)


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 1
    min_events = 1
    require_cat = ""
    require_arg = ""
    prom_label = ""
    prom_paths = []
    doctor_paths = []
    flow_paths = []
    endpoints = []
    if "--min-events" in argv:
        i = argv.index("--min-events")
        min_events = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    if "--require-cat" in argv:
        i = argv.index("--require-cat")
        require_cat = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if "--require-arg" in argv:
        i = argv.index("--require-arg")
        require_arg = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if "--prometheus-label" in argv:
        i = argv.index("--prometheus-label")
        prom_label = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    while "--prometheus" in argv:
        i = argv.index("--prometheus")
        prom_paths.append(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    while "--doctor" in argv:
        i = argv.index("--doctor")
        doctor_paths.append(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    while "--flow" in argv:
        i = argv.index("--flow")
        flow_paths.append(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    while "--endpoint" in argv:
        i = argv.index("--endpoint")
        endpoints.append(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    rc = 0
    for path in argv:
        try:
            spans, cats = check(path, min_events, require_cat,
                                require_arg)
            print(f"OK {path}: {spans} span events, "
                  f"categories: {', '.join(cats) or '(none)'}")
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL {path}: {e}", file=sys.stderr)
            rc = 1
    for path in prom_paths:
        try:
            n, fams = check_prometheus(path, prom_label)
            print(f"OK {path}: {n} samples, {len(fams)} families")
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL {path}: {e}", file=sys.stderr)
            rc = 1
    for path in doctor_paths:
        try:
            verdict, n = check_doctor(path)
            print(f"OK {path}: verdict {verdict}, {n} ranked entries")
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL {path}: {e}", file=sys.stderr)
            rc = 1
    for path in flow_paths:
        try:
            n, cross, pids = check_flow(path)
            print(f"OK {path}: {n} flow edge(s) "
                  f"({cross} cross-process) over {pids} process(es)")
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL {path}: {e}", file=sys.stderr)
            rc = 1
    for url in endpoints:
        try:
            desc = check_endpoint(url, prom_label)
            print(f"OK {url}: {desc}")
        except Exception as e:  # urllib raises many flavours
            print(f"FAIL {url}: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
