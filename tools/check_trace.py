#!/usr/bin/env python3
"""Validate an exported Chrome trace-event JSON file against the
trace-event schema subset the tracer emits (observability/export.py):
every event must carry ph/ts/pid/tid/name; "X" complete events must
carry a non-negative dur.  Used by ci/run_ci.sh after the traced-query
step and by tests/test_tracer.py.

Usage: python tools/check_trace.py <trace.json> [--min-events N]
           [--require-cat CAT]
``--require-cat`` additionally fails unless at least one span event
carries that category (e.g. ``fault`` for chaos-soak traces).
Exit 0 on a valid trace, 1 otherwise.
"""

import json
import sys

REQUIRED = ("ph", "ts", "pid", "tid", "name")
KNOWN_PH = ("X", "C", "i", "M", "B", "E")

#: categories the tracer emits today (observability/tracer.py
#: CATEGORIES); unknown categories stay opaque — listed for reference
#: and for --require-cat hints, not validated
KNOWN_CATS = ("op", "kernel_compile", "sync", "h2d", "d2h", "spill",
              "shuffle", "sem_wait", "fault", "queue", "encode", "stage")


def check(path: str, min_events: int = 1, require_cat: str = ""):
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list")
    spans = 0
    cats = set()
    for i, ev in enumerate(events):
        for field in REQUIRED:
            if field not in ev:
                raise ValueError(f"event {i} missing required field "
                                 f"{field!r}: {ev}")
        if ev["ph"] not in KNOWN_PH:
            raise ValueError(f"event {i} has unknown ph {ev['ph']!r}")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"event {i} ts is not numeric: {ev['ts']!r}")
        if ev["ph"] == "X":
            if "dur" not in ev or not isinstance(ev["dur"], (int, float)) \
                    or ev["dur"] < 0:
                raise ValueError(f"event {i} 'X' span needs dur >= 0: {ev}")
            spans += 1
            cats.add(ev.get("cat", ""))
    if spans < min_events:
        raise ValueError(f"expected at least {min_events} span event(s), "
                         f"found {spans}")
    if require_cat and require_cat not in cats:
        raise ValueError(
            f"no span event with category {require_cat!r} "
            f"(found: {sorted(c for c in cats if c)})")
    return spans, sorted(c for c in cats if c)


def main(argv) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 1
    min_events = 1
    require_cat = ""
    if "--min-events" in argv:
        i = argv.index("--min-events")
        min_events = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    if "--require-cat" in argv:
        i = argv.index("--require-cat")
        require_cat = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    rc = 0
    for path in argv:
        try:
            spans, cats = check(path, min_events, require_cat)
            print(f"OK {path}: {spans} span events, "
                  f"categories: {', '.join(cats) or '(none)'}")
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL {path}: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
