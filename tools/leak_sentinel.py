#!/usr/bin/env python3
"""Leak sentinel — the bounded long-soak leak check (ROADMAP item 1,
docs/robustness.md "query lifecycle").

Runs mixed multi-tenant traffic through one ServingEngine for N seconds
in WAVES — each wave runs the chaos suite's query mix concurrently per
tenant, optionally with the lifecycle fault legs armed (cooperative
cancels via ``query.cancel.race``, per-query deadlines, injected
``device.fatal`` exercising quarantine + probe recovery) — and samples
the process's resource gauges between waves:

* retention pin count (``memory/retention.py``),
* BufferCatalog registered handles (``leak_report()``),
* metrics-registry series cardinality (bounded by ``maxSeries``),
* encoded dictionary-registry size (``columnar/encoded.py``),
* tracer ring high-water (bounded by the ring capacity).

Verdict contract: after each wave (post shuffle TTL-sweep + gc) the
RESOURCE gauges (pins, catalog handles, dictionary registry) must return
to the post-warmup baseline, and the BOUNDED gauges (metrics series,
ring high-water) must respect their caps — a process serving millions of
users must look the same after wave 50 as after wave 1.

``--telemetry`` runs the soak with the embedded telemetry server
enabled (observability/server.py): /metrics and /healthz are scraped
mid-soak to prove the plane serves under load, and after engine close
the leg asserts the server left nothing behind — no lingering
``srt-telemetry-*`` thread and the port rebindable (the series-cap
bound already covers scrape-driven cardinality growth).

``--sentry`` runs a perf sentry daemon (observability/sentry.py)
alongside the soak — real cancellable device probes (which register
QueryContexts through the lifecycle plane) interleaved with simulated
window opens feeding a tiny fake bench — and after ``stop()`` asserts
the daemon drained to baseline: no lingering ``srt-sentry*`` thread, no
live ``sentry`` query contexts, and at least one valid ledger entry
appended.

``--cluster`` runs the pod-scale fault-domain leg: a real N-process
shuffle cluster (testing/chaos_cluster.py) through kill/recover cycles
— SIGKILL a peer mid-query, wait out the failure detector's dead
declaration, assert bit-identical recovery — and after each cluster
close asserts the fault-domain state drained to baseline: no lingering
``srt-peer-hb`` heartbeat threads, an empty detector peer table, and no
retained peer-epoch or block-source state on the closed manager.

Usage:  python tools/leak_sentinel.py [--seconds 60] [--tenants 2]
            [--rows 8000] [--arm cancel,deadline,fatal] [--telemetry]
            [--sentry] [--cluster] [--out FILE]
Exit 0 = clean verdict; 1 = leak (per-gauge evidence in the report).
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import tempfile
import threading
import time


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--seconds", type=float, default=60.0,
                   help="soak duration budget (waves stop after this)")
    p.add_argument("--tenants", type=int, default=2)
    p.add_argument("--rows", type=int, default=8000)
    p.add_argument("--max-waves", type=int, default=1000)
    p.add_argument("--arm", default="cancel,deadline,fatal",
                   help="comma list of lifecycle fault legs to arm: "
                        "cancel (query.cancel.race), deadline (a "
                        "deadline-doomed query per wave), fatal "
                        "(device.fatal -> quarantine + probe)")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--telemetry", action="store_true",
                   help="soak with the telemetry server enabled and "
                        "assert leak-free shutdown (thread + port)")
    p.add_argument("--sentry", action="store_true",
                   help="run a perf sentry daemon alongside the soak "
                        "and assert its thread + probe contexts drain "
                        "to baseline after stop()")
    p.add_argument("--cluster", action="store_true",
                   help="run N-process kill/recover cycles through the "
                        "chaos cluster harness and assert heartbeat "
                        "threads, the detector peer table and epoch "
                        "state drain to baseline on close")
    p.add_argument("--out", default="", help="write the JSON report here")
    return p


def _gauges() -> dict:
    """One sample of every leak-relevant gauge."""
    from spark_rapids_tpu.columnar import encoded as enc
    from spark_rapids_tpu.memory import retention
    from spark_rapids_tpu.memory.spill import BufferCatalog
    from spark_rapids_tpu.observability import tracer as OT
    from spark_rapids_tpu.observability.metrics import get_registry
    reg = get_registry()
    with reg._lock:
        series = (len(reg._counters) + len(reg._gauges)
                  + len(reg._hists))
    tr = OT.get_tracer()
    return {
        "retention_pins": retention.pinned_count(),
        "catalog_handles": len(BufferCatalog.get().leak_report()),
        "metrics_series": series,
        "dict_registry": len(enc._DICT_OBJECTS),
        "trace_ring_high_water": tr.high_water,
        "trace_ring_capacity": tr._events.maxlen,
    }


def run_cluster_leg(seconds: float, seed: int,
                    rows: int = 256) -> tuple:
    """Pod-scale fault-domain leg: kill/recover cycles through a REAL
    3-process shuffle cluster, asserting after every cluster close that
    the fault-domain state drained — no ``srt-peer-hb`` heartbeat
    threads beyond the pre-leg count, an empty detector peer table, and
    no retained peer-epoch / block-source state.  Returns
    (leg_report, leaks)."""
    from spark_rapids_tpu.robustness.failure_detector import THREAD_PREFIX
    from spark_rapids_tpu.testing.chaos_cluster import (ChaosCluster,
                                                        expected_digest)

    def hb_threads():
        return [t.name for t in threading.enumerate()
                if t.name.startswith(THREAD_PREFIX)]

    leaks = []
    baseline = len(hb_threads())
    detections, cycles = [], 0
    deadline = time.monotonic() + max(seconds, 1.0)
    while cycles == 0 or (cycles < 3 and time.monotonic() < deadline):
        cseed = seed + cycles
        exp = expected_digest(cseed, 3, rows)
        cl = ChaosCluster(3, cseed, rows)
        try:
            clean = cl.query()
            if any(r["digest"] != exp for r in clean):
                leaks.append(f"cluster cycle {cycles}: clean-run digest "
                             f"mismatch")
            cl.kill_victim()
            cl.expire_victim()
            detections.append(round(cl.wait_dead(), 1))
            degraded = cl.query(cl.survivors)
            if any(r["digest"] != exp for r in degraded):
                leaks.append(f"cluster cycle {cycles}: post-kill digest "
                             f"mismatch (recovery broke parity)")
        finally:
            mgr = cl.driver
            cl.close()
        # drain-to-baseline asserts (the leg's whole point): close()
        # must tear down the heartbeat loop, detector and fencing state
        grace = time.monotonic() + 5.0
        while len(hb_threads()) > baseline \
                and time.monotonic() < grace:
            time.sleep(0.05)
        left = hb_threads()
        if len(left) > baseline:
            leaks.append(f"cluster cycle {cycles}: heartbeat thread(s) "
                         f"lingering after close: {left}")
        if mgr.detector.peer_count() != 0:
            leaks.append(f"cluster cycle {cycles}: detector peer table "
                         f"not drained: {mgr.detector.snapshot()}")
        if mgr._peer_epochs:
            leaks.append(f"cluster cycle {cycles}: peer epochs retained "
                         f"after close: {mgr._peer_epochs}")
        if mgr._block_sources:
            leaks.append(f"cluster cycle {cycles}: block-source map "
                         f"retained after close")
        cycles += 1
    leg = {
        "cycles": cycles,
        "detection_ms": detections,
        "hb_threads_baseline": baseline,
        "hb_threads_final": len(hb_threads()),
        "shutdown": "clean" if not leaks else "leak",
    }
    return leg, leaks


def _scrape(host: str, port: int, route: str) -> tuple:
    """(status, body) from the embedded telemetry server; 503 on a
    degraded /healthz is a valid answer, not an error."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}{route}", timeout=5) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8", "replace")


def run_sentinel(seconds: float = 60.0, tenants: int = 2,
                 rows: int = 8000, seed: int = 11,
                 arm: str = "cancel,deadline,fatal",
                 max_waves: int = 1000,
                 telemetry: bool = False,
                 sentry: bool = False,
                 cluster: bool = False) -> dict:
    """Returns the report dict; report["verdict"] is "clean" or "leak"."""
    import spark_rapids_tpu as srt  # noqa: F401 - engine init path
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.memory.fatal import FatalDeviceError
    from spark_rapids_tpu.memory.spill import BufferCatalog
    from spark_rapids_tpu.robustness import disarm_chaos
    from spark_rapids_tpu.serving import ServingEngine
    from spark_rapids_tpu.serving import lifecycle as lc
    from spark_rapids_tpu.shuffle import get_shuffle_manager
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession
    from spark_rapids_tpu.testing.chaos import (QUERIES, _base_conf,
                                                _soak_tables)
    legs = {s.strip() for s in arm.split(",") if s.strip()}
    tables = _soak_tables(rows)
    tmp = tempfile.mkdtemp(prefix="srt-leak-")
    prev_active = TpuSession._active
    BufferCatalog.reset(RapidsConf(
        {"spark.rapids.memory.spillDir": tmp}))
    max_series = 4096
    eng_conf = dict(_base_conf(tmp))
    eng_conf.update({
        "spark.rapids.tpu.metrics.enabled": True,
        "spark.rapids.tpu.metrics.maxSeries": max_series,
        "spark.rapids.tpu.profile.enabled": True,
        "spark.rapids.tpu.serving.maxConcurrentQueries": max(2, tenants),
    })
    if telemetry:
        eng_conf.update({
            "spark.rapids.tpu.telemetry.enabled": True,
            "spark.rapids.tpu.telemetry.port": 0,  # ephemeral
        })
    typed = {"cancelled": 0, "deadline": 0, "fatal": 0, "quarantined": 0,
             "degraded_refusals": 0, "ok": 0, "unexpected": 0}
    eng = ServingEngine(conf=RapidsConf.get_global().copy(eng_conf))
    # shuffle state must not accrue for the soak's lifetime: reclaim
    # deferred shuffles immediately at each wave's sweep so "returns to
    # baseline" is meaningful (the default TTL parks them for an hour)
    get_shuffle_manager().cleanup_ttl_s = -1.0
    samples = []
    telem: dict = {}
    sentry_leg: dict = {}
    sentry_obj = None
    t_host, t_port = "", 0
    if sentry:
        from spark_rapids_tpu.observability import sentry as OS
        sdir = tempfile.mkdtemp(prefix="srt-sentry-leak-")
        probe_n = {"n": 0}

        def sentry_probe() -> dict:
            # every third attempt simulates an open window; the others
            # run the REAL cancellable device probe — on this CPU host
            # it classifies ``degraded``, exercising the QueryContext
            # register/poll/unregister path whose drain this leg asserts
            probe_n["n"] += 1
            if probe_n["n"] % 3 == 0:
                return {"outcome": "ok", "platform": "simulated",
                        "elapsed_ms": 0.1}
            return OS.device_probe(timeout_s=5.0)

        def sentry_bench(shapes) -> dict:
            return {"metric": "sentry_shape_set", "value": 1.0,
                    "unit": "rows/s", "rows": 1,
                    "platform": "simulated", "evidence": "live",
                    "shapes": list(shapes)}

        sentry_obj = OS.PerfSentry(
            probe=sentry_probe, bench=sentry_bench,
            ledger=os.path.join(sdir, "ledger.jsonl"),
            interval_s=0.2, probe_timeout_s=5.0,
            entry_extra={"simulated": True})
        sentry_obj.start()
        sentry_leg["ledger"] = sentry_obj.ledger.path
    try:
        if telemetry:
            if eng.telemetry is None:
                raise AssertionError("telemetry enabled but no server")
            t_host, t_port = eng.telemetry.host, eng.telemetry.port
            telem["endpoint"] = eng.telemetry.endpoint
        sessions = {f"tenant{i}": eng.session(tenant=f"tenant{i}")
                    for i in range(tenants)}
        if "deadline" in legs:
            # one doomed session per wave: a 1ms deadline on this suite
            # always expires at a poll site
            doomed = eng.session(
                tenant="tenant0",
                **{"spark.rapids.tpu.query.deadlineMs": 1})

        def run_wave(wave: int, armed: bool) -> None:
            errs: dict = {}

            def tenant_work(tname: str, sess) -> None:
                for qname, fn in QUERIES:
                    try:
                        fn(sess, tables, F)
                        typed["ok"] += 1
                    except lc.QueryCancelled:
                        # includes QueryDeadlineExceeded
                        typed["cancelled"] += 1
                    except lc.QueryQuarantined:
                        typed["quarantined"] += 1
                    except lc.EngineDegraded:
                        typed["degraded_refusals"] += 1
                    except FatalDeviceError:
                        typed["fatal"] += 1
                    except BaseException as e:  # noqa: BLE001
                        typed["unexpected"] += 1
                        errs[f"{tname}/{qname}"] = repr(e)

            threads = [threading.Thread(target=tenant_work,
                                        args=(t, s),
                                        name=f"leak-{t}")
                       for t, s in sessions.items()]
            for t in threads:
                t.start()
            if armed and "deadline" in legs:
                try:
                    QUERIES[0][1](doomed, tables, F)
                except lc.QueryCancelled:
                    typed["deadline"] += 1
                except (lc.EngineDegraded, lc.QueryQuarantined):
                    typed["degraded_refusals"] += 1
            if armed and "fatal" in legs and wave % 3 == 1:
                # one poisoned query per third wave: quarantine + the
                # probe-recovery path must also hold the baseline
                from spark_rapids_tpu.robustness import faults
                prev = faults.snapshot_arming()
                faults.arm_chaos(seed=seed + wave,
                                 sites="device.fatal:1.0")
                try:
                    QUERIES[1][1](sessions["tenant0"], tables, F)
                    typed["unexpected"] += 1
                except FatalDeviceError:
                    typed["fatal"] += 1
                except (lc.EngineDegraded, lc.QueryQuarantined):
                    typed["degraded_refusals"] += 1
                finally:
                    faults.restore_arming(prev)
            for t in threads:
                t.join()
            if errs:
                raise AssertionError(f"non-typed errors in wave: {errs}")

        def settle() -> None:
            get_shuffle_manager().sweep_deferred()
            gc.collect()

        # Three phases (the verdict contract):
        #   A. CLEAN warmup — caches (upload/kernel/dictionary, each
        #      session's retained last plan) reach their flat steady
        #      state; the baseline is those gauges.
        #   B. ARMED soak — cancel races, deadlines and fatal injection
        #      run for the time budget; gauges are sampled per wave
        #      (evidence, and the bounded-gauge caps are asserted here).
        #   C. CLEAN drain — faults disarmed, two healthy waves: every
        #      resource gauge must RETURN TO the phase-A baseline.  Any
        #      state a fault wave durably retained that healthy traffic
        #      cannot displace is a leak.
        from spark_rapids_tpu.robustness import faults as _faults
        for w in range(2):
            run_wave(w, armed=False)
        settle()
        baseline = _gauges()
        if "cancel" in legs:
            # per-CHECK probability: poll sites fire dozens of times per
            # query, so a small p cancels a healthy fraction of each
            # wave's queries without drowning the ok-path coverage
            _faults.arm_chaos(seed=seed, sites="query.cancel.race:0.01")
        t_end = time.monotonic() + seconds
        wave = 0
        while time.monotonic() < t_end and wave < max_waves:
            wave += 1
            run_wave(wave, armed=True)
            settle()
            samples.append(dict(_gauges(), wave=wave))
            if telemetry and wave == 1:
                # the plane must serve mid-soak; /healthz may honestly
                # answer 503 here (fatal legs degrade the engine)
                st, body = _scrape(t_host, t_port, "/metrics")
                telem["metrics_scrape"] = {
                    "status": st,
                    "lines": body.count("\n"),
                }
                telem["healthz_status"] = _scrape(
                    t_host, t_port, "/healthz")[0]
        _faults.disarm_chaos()
        for w in range(2):
            run_wave(wave + 1 + w, armed=False)
        settle()
        final = _gauges()
        leaks = []
        for g in ("retention_pins", "catalog_handles", "dict_registry"):
            if final[g] > baseline[g]:
                leaks.append(
                    f"{g} did not return to baseline after the clean "
                    f"drain: {final[g]} > {baseline[g]}")
        for s in samples:
            if s["metrics_series"] > max_series:
                leaks.append(f"wave {s['wave']}: metrics_series "
                             f"{s['metrics_series']} > bound {max_series}")
            if s["trace_ring_high_water"] > s["trace_ring_capacity"]:
                leaks.append(f"wave {s['wave']}: ring high-water over "
                             f"capacity")
        if telemetry:
            if telem.get("metrics_scrape", {}).get("status") != 200:
                leaks.append(
                    f"/metrics scrape mid-soak did not answer 200: "
                    f"{telem.get('metrics_scrape')}")
            # shutdown must be leak-free: close NOW (idempotent; the
            # finally re-closes harmlessly) and probe thread + port
            eng.close()
            import socket
            lingering = [t.name for t in threading.enumerate()
                         if t.name.startswith("srt-telemetry-")]
            if lingering:
                leaks.append(f"telemetry thread(s) lingering after "
                             f"engine close: {lingering}")
            try:
                probe = socket.socket()
                probe.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEADDR, 1)
                probe.bind((t_host or "127.0.0.1", t_port))
                probe.close()
            except OSError as e:
                leaks.append(f"telemetry port {t_port} still bound "
                             f"after engine close: {e}")
            telem["shutdown"] = "clean" if not any(
                "telemetry" in leak for leak in leaks) else "leak"
        if sentry:
            # the daemon must drain to baseline: stop() joins the loop
            # thread; probe threads are short-lived daemons and probe
            # QueryContexts must all be unregistered (a small grace
            # window lets an in-flight probe land)
            sentry_obj.stop(timeout=10.0)

            def _sentry_residue():
                threads = [t.name for t in threading.enumerate()
                           if t.name.startswith("srt-sentry")]
                ctxs = [q for q in lc.live_queries()
                        if q.session_id == "sentry"]
                return threads, ctxs

            grace = time.monotonic() + 5.0
            threads_left, ctxs_left = _sentry_residue()
            while (threads_left or ctxs_left) \
                    and time.monotonic() < grace:
                time.sleep(0.1)
                threads_left, ctxs_left = _sentry_residue()
            if threads_left:
                leaks.append(f"sentry thread(s) lingering after "
                             f"stop(): {threads_left}")
            if ctxs_left:
                leaks.append(
                    f"sentry probe QueryContext(s) still registered "
                    f"after stop(): "
                    f"{[(q.session_id, q.query_id) for q in ctxs_left]}")
            entries = sentry_obj.ledger.entries()
            if not entries:
                leaks.append("sentry soak appended no ledger entries")
            sentry_leg.update({
                "probe_attempts": probe_n["n"],
                "windows": sentry_obj.windows,
                "ledger_entries": len(entries),
                "shutdown": "clean" if not any(
                    "sentry" in leak for leak in leaks) else "leak",
            })
        cluster_leg = None
        if cluster:
            # the fault-domain leg runs after the engine soak (its own
            # subprocesses; the engine's gauges are already sampled)
            cluster_leg, cluster_leaks = run_cluster_leg(
                min(seconds, 30.0), seed)
            leaks.extend(cluster_leaks)
        report = {
            "schema": "srt-leak-sentinel/1",
            "verdict": "clean" if not leaks else "leak",
            "waves": wave,
            "tenants": tenants,
            "rows": rows,
            "armed": sorted(legs),
            "outcomes": typed,
            "baseline": baseline,
            "final": final,
            "samples": samples[-5:],
            "leaks": leaks,
        }
        if telemetry:
            report["telemetry"] = telem
        if sentry:
            report["sentry"] = sentry_leg
        if cluster_leg is not None:
            report["cluster"] = cluster_leg
        return report
    finally:
        if sentry_obj is not None:
            sentry_obj.stop(timeout=5.0)
        eng.close()
        disarm_chaos()
        BufferCatalog.reset()
        TpuSession._active = prev_active


def main() -> int:
    # runnable from anywhere: the engine lives one level up from tools/
    # (the api_validation.py pattern — the package is not pip-installed)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # the ambient sitecustomize may force the axon TPU tunnel; this rig
    # runs on the host platform unless told otherwise (chaos.main does
    # the same)
    plat = os.environ.get("SRT_SCALE_PLATFORM", "cpu")
    if plat == "cpu":
        from spark_rapids_tpu import pin_host_platform
        pin_host_platform()
    args = build_arg_parser().parse_args()
    report = run_sentinel(seconds=args.seconds, tenants=args.tenants,
                          rows=args.rows, seed=args.seed, arm=args.arm,
                          max_waves=args.max_waves,
                          telemetry=args.telemetry,
                          sentry=args.sentry,
                          cluster=args.cluster)
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
    if report["verdict"] != "clean":
        print("LEAK SENTINEL FAILED:", *report["leaks"], sep="\n  ",
              file=sys.stderr)
        return 1
    print(f"LEAK SENTINEL PASSED: {report['waves']} waves, "
          f"{report['outcomes']['ok']} ok / "
          f"{report['outcomes']['cancelled']} cancelled / "
          f"{report['outcomes']['deadline']} deadline / "
          f"{report['outcomes']['fatal']} fatal — all gauges at "
          f"baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
