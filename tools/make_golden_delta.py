"""Generate FOREIGN Delta tables for interop tests.

This script deliberately does NOT import spark_rapids_tpu: it composes
`_delta_log` actions by hand following the public Delta transaction-log
protocol (PROTOCOL.md: protocol / metaData with schemaString / add with
partitionValues + stats / remove / commitInfo) and writes data files with
pyarrow — i.e. the same byte-level shapes a Spark or delta-rs writer
produces.  The committed fixtures under tests/golden/delta/ are therefore
tables the engine did not write (VERDICT r2 #5 done-criteria).

Run from the repo root:  python tools/make_golden_delta.py
"""

import json
import os
import shutil
import uuid

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "golden", "delta")


def _log(table, version, actions):
    d = os.path.join(table, "_delta_log")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"{version:020d}.json"), "w") as fh:
        for a in actions:
            fh.write(json.dumps(a) + "\n")


def _commit_info(op):
    return {"commitInfo": {"timestamp": 1735689600000, "operation": op,
                           "engineInfo": "goldenGen/0.1 DeltaSpec/1"}}


def _schema_string(fields):
    return json.dumps({"type": "struct", "fields": [
        {"name": n, "type": t, "nullable": True, "metadata": {}}
        for n, t in fields]})


def _metadata(fields, partition_columns=()):
    return {"metaData": {
        "id": str(uuid.uuid4()),
        "format": {"provider": "parquet", "options": {}},
        "schemaString": _schema_string(fields),
        "partitionColumns": list(partition_columns),
        "configuration": {},
        "createdTime": 1735689600000,
    }}


def _write_parquet(table_dir, rel, tbl):
    full = os.path.join(table_dir, rel)
    os.makedirs(os.path.dirname(full), exist_ok=True)
    pq.write_table(tbl, full, compression="snappy")
    return os.path.getsize(full)


def _stats(tbl):
    s = {"numRecords": tbl.num_rows, "minValues": {}, "maxValues": {},
         "nullCount": {}}
    for name in tbl.column_names:
        col = tbl.column(name)
        s["nullCount"][name] = col.null_count
        if col.num_chunks and tbl.num_rows > col.null_count:
            vals = [v for v in col.to_pylist() if v is not None]
            s["minValues"][name] = min(vals)
            s["maxValues"][name] = max(vals)
    return s


def _add(rel, size, tbl, partition_values=None):
    return {"add": {
        "path": rel, "partitionValues": partition_values or {},
        "size": size, "modificationTime": 1735689600000,
        "dataChange": True, "stats": json.dumps(_stats(tbl)),
    }}


def make_people():
    """Unpartitioned table: 3 commits — create+2 files, append, delete
    (remove one file, add its filtered replacement)."""
    t = os.path.join(ROOT, "people")
    shutil.rmtree(t, ignore_errors=True)
    fields = [("id", "long"), ("name", "string"), ("score", "double")]

    f0 = pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                   "name": ["ada", "bob", "cat"],
                   "score": [9.5, 7.25, 8.0]})
    f1 = pa.table({"id": pa.array([4, 5], pa.int64()),
                   "name": ["dan", None],
                   "score": [6.5, 5.0]})
    r0 = f"part-00000-{uuid.uuid4()}-c000.snappy.parquet"
    r1 = f"part-00001-{uuid.uuid4()}-c000.snappy.parquet"
    _log(t, 0, [_commit_info("CREATE TABLE AS SELECT"),
                {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
                _metadata(fields),
                _add(r0, _write_parquet(t, r0, f0), f0),
                _add(r1, _write_parquet(t, r1, f1), f1)])

    f2 = pa.table({"id": pa.array([6, 7], pa.int64()),
                   "name": ["eve", "fay"],
                   "score": [9.9, 4.2]})
    r2 = f"part-00000-{uuid.uuid4()}-c000.snappy.parquet"
    _log(t, 1, [_commit_info("WRITE"),
                _add(r2, _write_parquet(t, r2, f2), f2)])

    # DELETE WHERE score < 7: rewrites f1 (drops id=4 with 6.5, id=5 w 5.0)
    # and f2 (drops id=7) — actually f1 drops BOTH rows -> pure remove
    f2b = f2.filter(pa.compute.greater_equal(f2.column("score"), 7.0))
    r2b = f"part-00000-{uuid.uuid4()}-c000.snappy.parquet"
    _log(t, 2, [_commit_info("DELETE"),
                {"remove": {"path": r1, "dataChange": True,
                            "deletionTimestamp": 1735689700000}},
                {"remove": {"path": r2, "dataChange": True,
                            "deletionTimestamp": 1735689700000}},
                _add(r2b, _write_parquet(t, r2b, f2b), f2b)])


def make_events():
    """Partitioned table: partition column `day` is NOT in the data files
    (real Delta stores it only in add.partitionValues)."""
    t = os.path.join(ROOT, "events")
    shutil.rmtree(t, ignore_errors=True)
    fields = [("ts", "long"), ("kind", "string"), ("day", "string")]
    rng = np.random.default_rng(7)
    actions = [_commit_info("CREATE TABLE AS SELECT"),
               {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
               _metadata(fields, partition_columns=["day"])]
    for day in ("2025-01-01", "2025-01-02"):
        n = 4
        data = pa.table({
            "ts": pa.array(rng.integers(0, 10_000, n), pa.int64()),
            "kind": pa.array(rng.choice(["click", "view"], n)),
        })  # note: no `day` column in the file
        rel = (f"day={day}/part-00000-{uuid.uuid4()}-c000.snappy.parquet")
        size = _write_parquet(t, rel, data)
        actions.append(_add(rel, size, data, {"day": day}))
    _log(t, 0, actions)


def make_unsupported():
    """A table requiring reader features this engine lacks (deletion
    vectors -> minReaderVersion 3): reads must FAIL loudly, not return
    wrong rows."""
    t = os.path.join(ROOT, "unsupported_dv")
    shutil.rmtree(t, ignore_errors=True)
    fields = [("x", "long")]
    f0 = pa.table({"x": pa.array([1, 2, 3], pa.int64())})
    r0 = f"part-00000-{uuid.uuid4()}-c000.snappy.parquet"
    _log(t, 0, [_commit_info("CREATE TABLE"),
                {"protocol": {"minReaderVersion": 3, "minWriterVersion": 7,
                              "readerFeatures": ["deletionVectors"],
                              "writerFeatures": ["deletionVectors"]}},
                _metadata(fields),
                _add(r0, _write_parquet(t, r0, f0), f0)])


if __name__ == "__main__":
    make_people()
    make_events()
    make_unsupported()
    print("golden delta tables written under", ROOT)
