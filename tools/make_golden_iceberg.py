"""Generate a FOREIGN Iceberg v2 table for interop tests.

Deliberately does NOT import spark_rapids_tpu: table metadata JSON is
composed straight from the Iceberg table-spec keys, and the avro manifest
list / manifests are written in the REAL nested layout
(``manifest_entry{status, snapshot_id, data_file: r2{...}}`` /
``manifest_file{manifest_path, ...}``) by a from-scratch minimal avro
container encoder below — i.e. the shapes a pyiceberg/Spark writer
produces.  Fixtures land in tests/golden/iceberg/ (VERDICT r2 #5).

Run from the repo root:  python tools/make_golden_iceberg.py
"""

import json
import os
import shutil
import struct
import uuid

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "golden", "iceberg")


# ---------------------------------------------------------------------------
# minimal avro encoder (independent of the engine's codec)
# ---------------------------------------------------------------------------

def _zigzag(out: bytearray, v: int) -> None:
    v = (v << 1) ^ (v >> 63)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            break


def _encode(out: bytearray, schema, value) -> None:
    if isinstance(schema, list):                      # union
        for i, branch in enumerate(schema):
            if (value is None) == (branch == "null"):
                _zigzag(out, i)
                if branch != "null":
                    _encode(out, branch, value)
                return
        raise ValueError(f"no union branch for {value!r} in {schema}")
    kind = schema["type"] if isinstance(schema, dict) else schema
    if kind in ("long", "int"):
        _zigzag(out, int(value))
    elif kind == "string":
        raw = value.encode("utf-8")
        _zigzag(out, len(raw))
        out.extend(raw)
    elif kind == "bytes":
        _zigzag(out, len(value))
        out.extend(value)
    elif kind == "boolean":
        out.append(1 if value else 0)
    elif kind == "double":
        out.extend(struct.pack("<d", float(value)))
    elif kind == "float":
        out.extend(struct.pack("<f", float(value)))
    elif kind == "record":
        for f in schema["fields"]:
            _encode(out, f["type"], value[f["name"]])
    elif kind == "array":
        if value:
            _zigzag(out, len(value))
            for item in value:
                _encode(out, schema["items"], item)
        _zigzag(out, 0)
    elif kind == "map":
        if value:
            _zigzag(out, len(value))
            for k, v in value.items():
                _encode(out, "string", k)
                _encode(out, schema["values"], v)
        _zigzag(out, 0)
    else:
        raise ValueError(f"unsupported avro type {schema!r}")


def write_avro_file(path: str, schema: dict, rows) -> None:
    sync = os.urandom(16)
    header = bytearray(b"Obj\x01")
    meta = {"avro.schema": json.dumps(schema), "avro.codec": "null"}
    _zigzag(header, len(meta))
    for k, v in meta.items():
        _encode(header, "string", k)
        raw = v.encode("utf-8")
        _zigzag(header, len(raw))
        header.extend(raw)
    _zigzag(header, 0)
    header.extend(sync)
    block = bytearray()
    for row in rows:
        _encode(block, schema, row)
    out = bytearray(header)
    _zigzag(out, len(rows))
    _zigzag(out, len(block))
    out.extend(block)
    out.extend(sync)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(bytes(out))


# ---------------------------------------------------------------------------
# real Iceberg v2 shapes
# ---------------------------------------------------------------------------

MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "record", "name": "r102", "fields": []}},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"],
         "default": None},
    ]}


def _entry(status, snapshot_id, path, content, records, size):
    return {"status": status, "snapshot_id": snapshot_id,
            "data_file": {"content": content, "file_path": path,
                          "file_format": "PARQUET", "partition": {},
                          "record_count": records,
                          "file_size_in_bytes": size}}


def make_orders():
    t = os.path.join(ROOT, "orders")
    shutil.rmtree(t, ignore_errors=True)
    rng = np.random.default_rng(9)

    def data_file(name, tbl):
        rel = f"data/{name}"
        full = os.path.join(t, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(tbl, full)
        return rel, os.path.getsize(full), tbl.num_rows

    # real Iceberg writers embed PARQUET:field_id into the data files;
    # projection resolves columns by id, not name
    def fid_schema(pairs):
        return pa.schema([
            pa.field(n, t, metadata={b"PARQUET:field_id":
                                     str(i).encode()})
            for i, (n, t) in enumerate(pairs, start=1)])

    sch = fid_schema([("order_id", pa.int64()), ("amount", pa.float64())])
    f0 = pa.table({"order_id": pa.array([1, 2, 3, 4], pa.int64()),
                   "amount": [10.0, 20.5, 30.0, 5.25]}).cast(sch)
    f1 = pa.table({"order_id": pa.array([5, 6], pa.int64()),
                   "amount": [99.0, 42.0]}).cast(sch)
    r0, s0, n0 = data_file(f"00000-0-{uuid.uuid4()}.parquet", f0)
    r1, s1, n1 = data_file(f"00001-0-{uuid.uuid4()}.parquet", f1)

    # snapshot 1: two data files
    m1 = f"metadata/{uuid.uuid4()}-m0.avro"
    write_avro_file(os.path.join(t, m1), MANIFEST_ENTRY_SCHEMA, [
        _entry(1, 1001, r0, 0, n0, s0),
        _entry(1, 1001, r1, 0, n1, s1)])
    l1 = "metadata/snap-1001-1-x.avro"
    write_avro_file(os.path.join(t, l1), MANIFEST_FILE_SCHEMA, [
        {"manifest_path": m1,
         "manifest_length": os.path.getsize(os.path.join(t, m1)),
         "partition_spec_id": 0, "added_snapshot_id": 1001}])

    # snapshot 2: position-delete of order_id=2 (file f0, pos 1)
    dtab = pa.table({"file_path": pa.array([r0], pa.string()),
                     "pos": pa.array([1], pa.int64())})
    rd, sd, nd = data_file(f"00002-deletes-{uuid.uuid4()}.parquet", dtab)
    m2 = f"metadata/{uuid.uuid4()}-m0.avro"
    write_avro_file(os.path.join(t, m2), MANIFEST_ENTRY_SCHEMA, [
        _entry(1, 1002, rd, 1, nd, sd)])
    l2 = "metadata/snap-1002-1-x.avro"
    write_avro_file(os.path.join(t, l2), MANIFEST_FILE_SCHEMA, [
        {"manifest_path": m1,
         "manifest_length": os.path.getsize(os.path.join(t, m1)),
         "partition_spec_id": 0, "added_snapshot_id": 1001},
        {"manifest_path": m2,
         "manifest_length": os.path.getsize(os.path.join(t, m2)),
         "partition_spec_id": 0, "added_snapshot_id": 1002}])

    meta = {
        "format-version": 2,
        "table-uuid": str(uuid.uuid4()),
        "location": "file:///warehouse/orders",
        "last-updated-ms": 1735689600000,
        "last-column-id": 2,
        "current-schema-id": 0,
        "schemas": [{"type": "struct", "schema-id": 0, "fields": [
            {"id": 1, "name": "order_id", "required": False,
             "type": "long"},
            {"id": 2, "name": "amount", "required": False,
             "type": "double"}]}],
        "default-spec-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": []}],
        "current-snapshot-id": 1002,
        "snapshots": [
            {"snapshot-id": 1001, "timestamp-ms": 1735689600000,
             "manifest-list": l1,
             "summary": {"operation": "append"}},
            {"snapshot-id": 1002, "timestamp-ms": 1735689700000,
             "manifest-list": l2,
             "summary": {"operation": "delete"}}],
        "snapshot-log": [
            {"snapshot-id": 1001, "timestamp-ms": 1735689600000},
            {"snapshot-id": 1002, "timestamp-ms": 1735689700000}],
        "properties": {"write.format.default": "parquet"},
    }
    d = os.path.join(t, "metadata")
    with open(os.path.join(d, "v2.metadata.json"), "w") as fh:
        json.dump(meta, fh, indent=1)
    with open(os.path.join(d, "version-hint.text"), "w") as fh:
        fh.write("2")


MANIFEST_ENTRY_SCHEMA_V2SEQ = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {"name": "sequence_number", "type": ["null", "long"],
         "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "record", "name": "r102", "fields": []}},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "equality_ids",
                 "type": ["null", {"type": "array", "items": "int"}],
                 "default": None},
            ]}},
    ]}


def _entry_v2(status, snapshot_id, seq, path, content, records, size,
              equality_ids=None):
    return {"status": status, "snapshot_id": snapshot_id,
            "sequence_number": seq,
            "data_file": {"content": content, "file_path": path,
                          "file_format": "PARQUET", "partition": {},
                          "record_count": records,
                          "file_size_in_bytes": size,
                          "equality_ids": equality_ids}}


def make_orders_eqdel():
    """orders_eqdel: snapshot 1 appends two data files (seq 1), snapshot 2
    commits an EQUALITY delete on order_id (seq 2) removing ids 2 and 5 —
    the v2 row-level delete shape the reference applies via
    GpuDeleteFilter.equalityFieldIds."""
    t = os.path.join(ROOT, "orders_eqdel")
    shutil.rmtree(t, ignore_errors=True)

    def data_file(name, tbl):
        rel = f"data/{name}"
        full = os.path.join(t, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(tbl, full)
        return rel, os.path.getsize(full), tbl.num_rows

    def fid_schema(pairs):
        return pa.schema([
            pa.field(n, ty, metadata={b"PARQUET:field_id":
                                      str(i).encode()})
            for i, (n, ty) in enumerate(pairs, start=1)])

    sch = fid_schema([("order_id", pa.int64()), ("amount", pa.float64())])
    f0 = pa.table({"order_id": pa.array([1, 2, 3, 4], pa.int64()),
                   "amount": [10.0, 20.5, 30.0, 5.25]}).cast(sch)
    f1 = pa.table({"order_id": pa.array([5, 6], pa.int64()),
                   "amount": [99.0, 42.0]}).cast(sch)
    r0, s0, n0 = data_file(f"00000-0-{uuid.uuid4()}.parquet", f0)
    r1, s1, n1 = data_file(f"00001-0-{uuid.uuid4()}.parquet", f1)

    # real writers leave ADDED entries' sequence_number NULL and rely on
    # v2 inheritance from the committing snapshot — the reader must
    # resolve these to snapshot 2001's sequence (1), not 0
    m1 = f"metadata/{uuid.uuid4()}-m0.avro"
    write_avro_file(os.path.join(t, m1), MANIFEST_ENTRY_SCHEMA_V2SEQ, [
        _entry_v2(1, 2001, None, r0, 0, n0, s0),
        _entry_v2(1, 2001, None, r1, 0, n1, s1)])
    l1 = "metadata/snap-2001-1-x.avro"
    write_avro_file(os.path.join(t, l1), MANIFEST_FILE_SCHEMA, [
        {"manifest_path": m1,
         "manifest_length": os.path.getsize(os.path.join(t, m1)),
         "partition_spec_id": 0, "added_snapshot_id": 2001}])

    # equality delete on field id 1 (order_id): drop ids 2 and 5 —
    # written under a HISTORICAL column name to force field-id matching
    dsch = pa.schema([pa.field("order_id_v1", pa.int64(),
                               metadata={b"PARQUET:field_id": b"1"})])
    dtab = pa.table({"order_id_v1": pa.array([2, 5], pa.int64())}).cast(dsch)
    rd, sd, nd = data_file(f"00002-eqdel-{uuid.uuid4()}.parquet", dtab)
    m2 = f"metadata/{uuid.uuid4()}-m0.avro"
    write_avro_file(os.path.join(t, m2), MANIFEST_ENTRY_SCHEMA_V2SEQ, [
        _entry_v2(1, 2002, 2, rd, 2, nd, sd, equality_ids=[1])])
    l2 = "metadata/snap-2002-1-x.avro"
    write_avro_file(os.path.join(t, l2), MANIFEST_FILE_SCHEMA, [
        {"manifest_path": m1,
         "manifest_length": os.path.getsize(os.path.join(t, m1)),
         "partition_spec_id": 0, "added_snapshot_id": 2001},
        {"manifest_path": m2,
         "manifest_length": os.path.getsize(os.path.join(t, m2)),
         "partition_spec_id": 0, "added_snapshot_id": 2002}])

    meta = {
        "format-version": 2,
        "table-uuid": str(uuid.uuid4()),
        "location": "file:///warehouse/orders_eqdel",
        "last-updated-ms": 1735689600000,
        "last-column-id": 2,
        "last-sequence-number": 2,
        "current-schema-id": 0,
        "schemas": [{"type": "struct", "schema-id": 0, "fields": [
            {"id": 1, "name": "order_id", "required": False,
             "type": "long"},
            {"id": 2, "name": "amount", "required": False,
             "type": "double"}]}],
        "default-spec-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": []}],
        "current-snapshot-id": 2002,
        "snapshots": [
            {"snapshot-id": 2001, "timestamp-ms": 1735689600000,
             "sequence-number": 1, "manifest-list": l1,
             "summary": {"operation": "append"}},
            {"snapshot-id": 2002, "timestamp-ms": 1735689700000,
             "sequence-number": 2, "manifest-list": l2,
             "summary": {"operation": "delete"}}],
        "snapshot-log": [
            {"snapshot-id": 2001, "timestamp-ms": 1735689600000},
            {"snapshot-id": 2002, "timestamp-ms": 1735689700000}],
        "properties": {"write.format.default": "parquet"},
    }
    d = os.path.join(t, "metadata")
    with open(os.path.join(d, "v2.metadata.json"), "w") as fh:
        json.dump(meta, fh, indent=1)
    with open(os.path.join(d, "version-hint.text"), "w") as fh:
        fh.write("2")


if __name__ == "__main__":
    make_orders()
    make_orders_eqdel()
    print("golden iceberg table written under", ROOT)
