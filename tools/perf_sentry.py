#!/usr/bin/env python3
"""Perf sentry CLI — the unattended live-window capture daemon.

Drives spark_rapids_tpu/observability/sentry.py end to end with zero
manual steps: probe the device tunnel on an exponential-backoff cadence
(cancellable, bounded-timeout, every attempt classified and banked), and
on a live window run the bench shape set, bench_diff it against the last
live-evidence baseline auto-resolved from the evidence ledger, and
append the srt-ledger/1 record (artifact path, evidence class,
regression verdicts, doctor verdict, machine-named follow-up).

tools/tunnel_watcher.sh is a thin wrapper over this CLI.

Usage:
  python tools/perf_sentry.py --daemon [--force] [--full-capture]
  python tools/perf_sentry.py --once [--force]
  python tools/perf_sentry.py --simulate-window [--windows 2]
  python tools/perf_sentry.py --status

Modes:
  --daemon            loop forever (probe cadence with backoff); the
                      default when no mode flag is given
  --once              one probe tick; on a live window one full capture
                      cycle.  Exit 0 when a ledger entry was appended,
                      1 when no window opened.
  --simulate-window   fake an open window (probe always ok) and run the
                      shape set in-process at small row counts with
                      evidence forced to 'live' and the ledger record
                      honestly marked "simulated": true — the CI e2e
                      mode.  Implies --once semantics; --windows N runs
                      N back-to-back windows (so window 2 diffs against
                      window 1's entry).
  --status            print the srt-sentry/1 status payload for the
                      configured ledger and exit

Flags:
  --force             run even with spark.rapids.tpu.sentry.enabled
                      false (the conf gate guards implicit startups,
                      not an operator invoking the CLI directly)
  --full-capture      after the sentry's own shape-set capture on a
                      live window, also run the legacy full capture
                      cycle (bench.py main/warm/suite + leak-sentinel
                      soak into .bench_capture/, throttled to once per
                      2h, mkdir-mutexed) so bench.py's replay fallback
                      keeps being fed
  --ledger PATH       evidence ledger (default: conf ledgerPath, else
                      .bench_capture/ledger.jsonl)
  --shapes CSV        shape subset (default: conf sentry.shapes)
  --rows N            shape-set row count
  --interval-s S      probe interval (default: conf probeIntervalMs)
  --probe-timeout-s S probe deadline (default: conf probeTimeoutMs)
  --budget-s S        shape-set watchdog budget
  --serve-port P      also serve the telemetry plane (incl. /sentry) on
                      127.0.0.1:P while running
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from spark_rapids_tpu.observability import sentry as S  # noqa: E402


def _log(msg: str) -> None:
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(f"{ts} {msg}", flush=True)


# --------------------------------------------------------------------------
# legacy full-capture cycle (ported from tools/tunnel_watcher.sh)
# --------------------------------------------------------------------------

def full_capture_cycle(cap_dir: str) -> str:
    """The watcher's capture payload: bench.py main/warm/suite runs plus
    a leak-sentinel soak, banked under ``cap_dir`` for bench.py's replay
    fallback.  Throttled to once per 2h via ``capture_done``; mutexed
    via a ``capture_running`` mkdir (one syscall test-and-set — two
    sentries on one chip must not bank contended numbers as evidence).
    Returns ``done | fruitless | throttled | locked``."""
    os.makedirs(cap_dir, exist_ok=True)
    done_stamp = os.path.join(cap_dir, "capture_done")
    lock = os.path.join(cap_dir, "capture_running")
    try:
        if os.path.exists(done_stamp) \
                and time.time() - os.path.getmtime(done_stamp) < 7200:
            return "throttled"
        # clear a stale lock (a capture should never exceed ~4h)
        if os.path.isdir(lock) \
                and time.time() - os.path.getmtime(lock) > 14400:
            os.rmdir(lock)
    except OSError:
        pass
    try:
        os.mkdir(lock)
    except OSError:
        return "locked"
    cycle_files = []
    try:
        # main FIRST: .jax_cache already holds the warm programs from
        # earlier windows, and tunnel windows can be short — the 8M-row
        # headline number must not wait behind a warm-up run
        for mode, budget, extra in (("main", 1800, []),
                                    ("warm", 1200, ["2000000"]),
                                    ("suite", 3600, ["--suite"])):
            ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            _log(f"capture {mode} start")
            env = dict(os.environ,
                       BENCH_BUDGET_S=str(budget),
                       SRT_BENCH_TELEMETRY_DIR=os.path.join(
                           cap_dir, f"telemetry_{ts}_{mode}"))
            out_path = os.path.join(cap_dir, f"run_{ts}_{mode}.out")
            with open(out_path, "w") as out, \
                    open(os.path.join(
                        cap_dir, f"run_{ts}_{mode}.err"), "w") as err:
                try:
                    subprocess.run(
                        [sys.executable,
                         os.path.join(_REPO, "bench.py")] + extra,
                        cwd=_REPO, env=env, stdout=out, stderr=err,
                        timeout=budget + 100)
                except subprocess.TimeoutExpired:
                    pass  # bench's own watchdog already banked partials
            cycle_files.append(out_path)
            _log(f"capture {mode} done")
        # leak-sentinel soak on the SAME live window: short and last —
        # the bench numbers above must never wait behind a soak
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        _log("capture soak start")
        try:
            subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "leak_sentinel.py"),
                 "--seconds", "600", "--tenants", "2", "--rows", "8000",
                 "--out", os.path.join(cap_dir, f"soak_{ts}.json")],
                cwd=_REPO, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, timeout=700)
        except subprocess.TimeoutExpired:
            pass
        _log("capture soak done")
        # stamp capture_done ONLY if the cycle banked a record bench.py's
        # replay will accept (same predicate — the two can never drift)
        import bench  # parent-safe: bench.py never imports jax at import
        usable = False
        for path in cycle_files:
            try:
                with open(path) as fh:
                    for line in fh:
                        line = line.strip()
                        if not line.startswith("{"):
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if bench._usable_capture_record(rec):
                            usable = True
            except OSError:
                pass
        if usable:
            with open(done_stamp, "w") as fh:
                fh.write(time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()) + "\n")
            return "done"
        _log("capture cycle banked no on-chip record")
        return "fruitless"
    finally:
        try:
            os.rmdir(lock)
        except OSError:
            pass


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def build_sentry(args: argparse.Namespace) -> S.PerfSentry:
    overrides = {}
    if args.ledger:
        overrides["ledger"] = args.ledger
    if args.shapes:
        overrides["shapes"] = [s.strip() for s in args.shapes.split(",")
                               if s.strip()]
    if args.rows:
        overrides["rows"] = args.rows
    if args.interval_s is not None:
        overrides["interval_s"] = args.interval_s
    if args.probe_timeout_s is not None:
        overrides["probe_timeout_s"] = args.probe_timeout_s
    if args.budget_s is not None:
        overrides["bench_budget_s"] = args.budget_s
    if args.simulate_window:
        rows = args.rows or 50_000
        budget = args.budget_s or 240.0
        overrides["probe"] = lambda: {"outcome": "ok",
                                      "platform": "simulated",
                                      "elapsed_ms": 0.1}
        overrides["bench"] = lambda shapes: S.run_shape_set_inprocess(
            shapes, rows=rows, budget_s=budget, evidence="live")
        overrides["entry_extra"] = {"simulated": True}
    else:
        # the daemon process stays jax-free: probe and shape set both
        # run in throwaway subprocesses (a wedged tunnel kills a child)
        overrides.setdefault(
            "probe", lambda: S.subprocess_probe(
                args.probe_timeout_s
                if args.probe_timeout_s is not None else 30.0))
    return S.PerfSentry.from_conf(**overrides)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_sentry",
        description="autonomous live-window perf capture daemon")
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--daemon", action="store_true")
    mode.add_argument("--once", action="store_true")
    mode.add_argument("--status", action="store_true")
    p.add_argument("--simulate-window", action="store_true")
    p.add_argument("--windows", type=int, default=1,
                   help="simulated windows to run back-to-back")
    p.add_argument("--force", action="store_true")
    p.add_argument("--full-capture", action="store_true")
    p.add_argument("--ledger")
    p.add_argument("--shapes")
    p.add_argument("--rows", type=int)
    p.add_argument("--interval-s", type=float)
    p.add_argument("--probe-timeout-s", type=float)
    p.add_argument("--budget-s", type=float)
    p.add_argument("--serve-port", type=int)
    p.add_argument("--json", action="store_true",
                   help="print appended ledger entries as JSON lines")
    args = p.parse_args(argv)

    if args.status:
        led = S.EvidenceLedger(args.ledger)
        payload = {
            "schema": S.STATUS_SCHEMA, "phase": "none",
            "running": False,
            "note": "CLI status for the on-disk ledger",
            "ledger": {"path": led.path, "entries": len(led.entries()),
                       "tail": led.tail(5)},
            "last_live_age_s": led.last_live_age_s(),
        }
        print(json.dumps(payload, indent=1, default=str))
        return 0

    if not (args.force or args.simulate_window) \
            and not S.PerfSentry.enabled():
        print("sentry disabled (spark.rapids.tpu.sentry.enabled=false);"
              " pass --force, or enable the conf", file=sys.stderr)
        return 2

    sentry = build_sentry(args)
    S.set_active(sentry)
    server = None
    if args.serve_port is not None:
        from spark_rapids_tpu.observability.metrics import get_registry
        from spark_rapids_tpu.observability.server import TelemetryServer
        server = TelemetryServer(
            metrics_text=lambda: get_registry().prometheus_text(),
            healthz=lambda: (True, {"sentry": sentry.phase}),
            queries=lambda: [],
            doctor=lambda: {"note": "standalone sentry process"},
            slo=lambda: {},
            port=args.serve_port)
        _log(f"telemetry plane (incl. /sentry) at {server.endpoint}")

    try:
        if args.once or args.simulate_window:
            appended = 0
            for _ in range(max(1, args.windows
                               if args.simulate_window else 1)):
                entry = sentry.run_once()
                if entry is not None:
                    appended += 1
                    if args.json:
                        print(json.dumps(entry, default=str))
                    else:
                        _log(f"ledger entry appended: "
                             f"evidence={entry.get('evidence')} "
                             f"diff={entry.get('diff', {}).get('verdict')} "
                             f"followup={entry.get('followup')!r}")
                    if args.full_capture:
                        _log("full capture cycle: "
                             + full_capture_cycle(
                                 os.path.dirname(os.path.abspath(
                                     sentry.ledger.path))))
                else:
                    last = (sentry.probe_attempts or [{}])[-1]
                    _log(f"no window: probe outcome="
                         f"{last.get('outcome')} "
                         f"next_delay_s={sentry.backoff_s:.0f} "
                         f"error={last.get('error')}")
            return 0 if appended else 1

        # daemon: synchronous loop (not .start()) so --full-capture can
        # run between windows without racing the sentry thread
        _log(f"sentry daemon up: interval={sentry.interval_s:.0f}s "
             f"probe_timeout={sentry.probe_timeout_s:.0f}s "
             f"shapes={','.join(sentry.shapes)} "
             f"ledger={sentry.ledger.path}")
        while True:
            entry = sentry.run_once()
            if entry is not None:
                _log(f"window captured: artifact="
                     f"{entry.get('artifact')} "
                     f"diff={entry.get('diff', {}).get('verdict')} "
                     f"followup={entry.get('followup')!r}")
                if args.full_capture:
                    _log("full capture cycle: "
                         + full_capture_cycle(os.path.dirname(
                             os.path.abspath(sentry.ledger.path))))
            else:
                last = (sentry.probe_attempts or [{}])[-1]
                _log(f"probe {last.get('outcome')}: next in "
                     f"{sentry.backoff_s:.0f}s")
            time.sleep(max(0.05, sentry.backoff_s))
    except KeyboardInterrupt:
        _log("interrupted; shutting down")
        return 0
    finally:
        S.set_active(None)
        if server is not None:
            server.close()


if __name__ == "__main__":
    sys.exit(main())
