"""Measure device regex coverage over the reference's test corpus.

Extracts candidate patterns from the reference's regex suites
(`tests/.../RegularExpressionTranspilerSuite.scala` + Parser/Regression
suites — the same corpus the reference validates its own transpiler on,
VERDICT r2 #8), keeps the ones that are valid Java-style regexes (proxy:
Python `re` compiles them), and reports what fraction this engine's DFA
accepts on-device, by mode:

  rlike   — membership only (search_prefix=True)
  extent  — span-consuming callers (replace/extract/split) that also
            need Java/POSIX extent agreement (extent_exact=True)

Rejection reasons are bucketed so the top lift targets are visible.
Writes docs/regex_coverage.md.

Run from the repo root:  python tools/regex_coverage.py [ref_root]
"""

from __future__ import annotations

import codecs
import collections
import os
import re
import sys

SUITES = [
    "tests/src/test/scala/com/nvidia/spark/rapids/"
    "RegularExpressionTranspilerSuite.scala",
    "tests/src/test/scala/com/nvidia/spark/rapids/"
    "RegularExpressionParserSuite.scala",
    "tests/src/test/scala/com/nvidia/spark/rapids/"
    "RegularExpressionSuite.scala",
]


def extract_corpus(ref_root: str):
    """Quoted string literals from the suites that compile as regexes."""
    pats = set()
    for rel in SUITES:
        path = os.path.join(ref_root, rel)
        if not os.path.exists(path):
            continue
        src = open(path, encoding="utf-8").read()
        for m in re.finditer(r'"((?:[^"\\]|\\.)*)"', src):
            raw = m.group(1)
            if not raw or len(raw) > 80:
                continue
            try:  # Scala string escapes -> actual chars (\\d -> \d, ...)
                lit = codecs.decode(raw, "unicode_escape")
            except Exception:
                continue
            if not lit.strip():
                continue
            if "${" in lit:      # Scala string-interpolation fragment,
                continue         # not a regex pattern
            try:
                re.compile(lit)
            except re.error:
                continue
            # skip obvious prose (sentences from assertion messages)
            if " " in lit and not any(c in lit for c in r"\[](){}|+*?^$."):
                continue
            pats.add(lit)
    return sorted(pats)


def measure(patterns):
    from spark_rapids_tpu.ops.regex_engine import (RegexUnsupported,
                                                   compile_regex)
    results = {}
    for mode, kwargs in [("rlike", {"search_prefix": True}),
                         ("extent", {"search_prefix": False,
                                     "extent_exact": True})]:
        ok = 0
        reasons = collections.Counter()
        fails = collections.defaultdict(list)
        for p in patterns:
            try:
                compile_regex(p, **kwargs)
                ok += 1
            except RegexUnsupported as e:
                key = _bucket(str(e))
                reasons[key] += 1
                if len(fails[key]) < 5:
                    fails[key].append(p)
            except Exception as e:  # parser crash = a bug, count separately
                reasons[f"CRASH {type(e).__name__}"] += 1
                if len(fails[f"CRASH {type(e).__name__}"]) < 5:
                    fails[f"CRASH {type(e).__name__}"].append(p)
        results[mode] = (ok, reasons, fails)
    return results


def _bucket(msg: str) -> str:
    msg = re.sub(r" at \d+ in .*$", "", msg)
    return msg[:70]


def main():
    ref_root = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    patterns = extract_corpus(ref_root)
    results = measure(patterns)
    lines = ["# Device regex coverage",
             "",
             f"Corpus: {len(patterns)} valid patterns extracted from the "
             "reference's regex test suites "
             "(RegularExpressionTranspilerSuite & co).", ""]
    for mode, (ok, reasons, fails) in results.items():
        pct = 100.0 * ok / max(len(patterns), 1)
        lines.append(f"## mode `{mode}`: {ok}/{len(patterns)} "
                     f"on device ({pct:.1f}%)")
        lines.append("")
        lines.append("| rejection reason | count | examples |")
        lines.append("|---|---|---|")
        for reason, count in reasons.most_common():
            ex = ", ".join(f"`{p}`".replace("|", "\\|")
                           for p in fails[reason][:3])
            lines.append(f"| {reason.replace('|', chr(92)+'|')} "
                         f"| {count} | {ex} |")
        lines.append("")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "regex_coverage.md")
    with open(out, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    for mode, (ok, _r, _f) in results.items():
        print(f"{mode}: {ok}/{len(patterns)} "
              f"({100.0 * ok / max(len(patterns), 1):.1f}%)")
    print("wrote", out)


if __name__ == "__main__":
    main()
