#!/usr/bin/env python3
"""Merge N per-process JSONL trace event logs into ONE Perfetto-loadable
Chrome trace with per-process tracks and cross-process flow events.

Each input is an event log written by observability/export.py
(``write_event_log``: a ``{"meta": ...}`` header per query followed by
raw tracer events).  The merge:

* gives every source process its own pid track (from the log's meta,
  de-colliding copies) with ``process_name``/``process_labels``/
  ``thread_name`` metadata;
* aligns timelines onto one clock: each log's event timestamps are
  µs from its own trace epoch, so events shift by the wall-clock delta
  between that epoch and the earliest epoch across all logs;
* stitches the distributed trace context the shuffle wire propagates
  (shuffle/tcp.py op 4, shuffle/serializer.py frame schema): spans
  carrying ``args.span_id`` are flow SOURCES (the requester's
  ``shuffle.fetch.remote``, the producer's ``serialize_batch``); spans
  carrying ``args.parent_span`` / ``args.producer_span`` naming such an
  id are flow SINKS (the peer's ``shuffle.serve``, the consumer's
  ``deserialize_batch``).  Every matched pair emits a Chrome flow start
  (``ph: "s"``) anchored on the source span and a binding-enclosing
  finish (``ph: "f"``, ``bp: "e"``) on the sink span, which Perfetto
  renders as an arrow from the requester's fetch to the peer's serve.

Usage:  python tools/trace_merge.py OUT.json LOG1.jsonl LOG2.jsonl ...
Prints a one-line summary (processes, spans, flows); exits non-zero on
unreadable input.  Validate the output with
``python tools/check_trace.py OUT.json --flow``.
"""

from __future__ import annotations

import json
import os
import sys
import zlib
from typing import Any, Dict, List


def merge(paths: List[str]) -> Dict[str, Any]:
    """Merged Chrome trace object for the given event logs."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from spark_rapids_tpu.observability.export import read_event_log

    entries = []  # (pid, meta, events) — one per appended query per file
    used_pids: Dict[int, str] = {}
    for fi, path in enumerate(paths):
        for meta, events in read_event_log(path):
            pid = int(meta.get("pid", 0)) or (90000 + fi)
            # two logs from the same pid are genuinely one process's
            # tracks; a COPIED log (same pid, different file AND epoch)
            # would interleave misleadingly — offset it to its own track
            owner = used_pids.setdefault(pid, path)
            if owner != path and not _same_process(entries, pid, meta):
                pid = pid + 100000 * (fi + 1)
            entries.append((pid, meta, events))
    if not entries:
        raise ValueError("no event-log entries in inputs")

    epoch0 = min(float(m.get("epoch_unix_s", 0.0)) for _, m, _ in entries)
    out: List[Dict[str, Any]] = []
    span_index: List[Dict[str, Any]] = []
    named_pids: set = set()
    for pid, meta, events in entries:
        shift_us = (float(meta.get("epoch_unix_s", 0.0)) - epoch0) * 1e6
        tid_map: Dict[Any, int] = {}
        for ev in events:
            raw_tid = ev.get("tid", 0)
            tid = tid_map.get(raw_tid)
            if tid is None:
                tid = tid_map[raw_tid] = len(tid_map)
            args = dict(ev.get("args") or {})
            if ev.get("exec"):
                args["exec"] = ev["exec"]
            if ev.get("tenant"):
                args["tenant"] = ev["tenant"]
            if ev.get("sid"):
                args["sid"] = ev["sid"]
            span = {
                "ph": "X", "cat": ev.get("cat", ""), "name": ev["name"],
                "ts": round(float(ev["ts"]) + shift_us, 3),
                "dur": round(float(ev.get("dur", 0.0)), 3),
                "pid": pid, "tid": tid, "args": args,
            }
            out.append(span)
            if args.get("span_id") or args.get("parent_span") \
                    or args.get("producer_span"):
                span_index.append(span)
        if pid not in named_pids:
            named_pids.add(pid)
            label = meta.get("session_id", "")
            out.append({"ph": "M", "name": "process_name", "ts": 0,
                        "pid": pid, "tid": 0,
                        "args": {"name": "spark_rapids_tpu"}})
            out.append({"ph": "M", "name": "process_labels", "ts": 0,
                        "pid": pid, "tid": 0,
                        "args": {"labels":
                                 f"pid={pid}"
                                 + (f" session={label}" if label else "")}})
        for raw, t in tid_map.items():
            out.append({"ph": "M", "name": "thread_name", "ts": 0,
                        "pid": pid, "tid": t,
                        "args": {"name": f"thread-{t} ({raw})"}})

    flows = _stitch(span_index, out)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"merged_from": [os.path.basename(p)
                                          for p in paths],
                          "processes": sorted(named_pids),
                          "flows": flows}}


def _same_process(entries, pid: int, meta) -> bool:
    """Same pid across files counts as one process only when the trace
    epochs agree (a multi-query sink directory from one process)."""
    for p, m, _ in entries:
        if p == pid and abs(float(m.get("epoch_unix_s", 0.0))
                            - float(meta.get("epoch_unix_s", 0.0))) < 1e-6:
            return True
    return False


def _stitch(span_index: List[Dict[str, Any]],
            out: List[Dict[str, Any]]) -> int:
    """Emit s/f flow-event pairs for every sink span whose parent/
    producer span id resolves to a source span."""
    sources: Dict[str, Dict[str, Any]] = {}
    for span in span_index:
        sid = span["args"].get("span_id")
        if sid:
            sources[str(sid)] = span
    flows = 0
    for span in span_index:
        ref = span["args"].get("parent_span") \
            or span["args"].get("producer_span")
        src = sources.get(str(ref)) if ref else None
        if src is None or src is span:
            continue
        # stable id per edge; cat/name must match across the s/f pair
        fid = zlib.crc32(f"{ref}->{span['pid']}/{span['ts']}".encode())
        trace_id = span["args"].get("trace_id") \
            or span["args"].get("producer_trace") or ""
        common = {"cat": "shuffle_flow", "name": "shuffle.edge",
                  "id": fid, "args": {"trace_id": trace_id}}
        out.append(dict(common, ph="s", pid=src["pid"], tid=src["tid"],
                        ts=src["ts"]))
        out.append(dict(common, ph="f", bp="e", pid=span["pid"],
                        tid=span["tid"], ts=span["ts"]))
        flows += 1
    return flows


def main(argv: List[str]) -> int:
    if len(argv) < 2 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 1
    out_path, inputs = argv[0], argv[1:]
    try:
        doc = merge(inputs)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace_merge: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    with open(out_path, "w") as fh:
        json.dump(doc, fh)
    od = doc["otherData"]
    spans = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
    print(f"OK {out_path}: {len(od['processes'])} process(es), "
          f"{spans} spans, {od['flows']} flow edge(s) "
          f"from {len(inputs)} log(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
