#!/bin/bash
# Tunnel watcher — now a thin wrapper over the perf sentry CLI.
#
# The round-4 shell loop (probe every 8 minutes with a hard subprocess
# timeout, full bench payload on the first live window) grew into a real
# subsystem: spark_rapids_tpu/observability/sentry.py detects live
# windows with cancellable bounded-timeout probes (classified outcomes,
# exponential backoff, telemetry banked), captures the bench shape set
# under per-shape watchdogs, bench_diffs against the last live-evidence
# baseline auto-resolved from the append-only evidence ledger
# (.bench_capture/ledger.jsonl, srt-ledger/1), and appends the record
# with the doctor's verdict and a machine-named follow-up.
#
# --full-capture keeps the legacy payload too: bench.py main/warm/suite
# runs plus the leak-sentinel soak banked under .bench_capture/ (2h
# throttle, mkdir mutex) so bench.py's replay fallback keeps being fed.
#
# Logs go to stdout; redirect as before:
#   nohup tools/tunnel_watcher.sh >> /tmp/tunnel_status.log 2>&1 &
REPO="$(cd "$(dirname "$0")/.." && pwd)"
exec python "$REPO/tools/perf_sentry.py" --daemon --force --full-capture "$@"
