#!/bin/bash
# Tunnel watcher — the round-4 answer to VERDICT r3 "Missing #1": three
# rounds of BENCH_r*.json carry zero on-chip numbers because the flaky
# axon TPU tunnel was only probed at driver time.  This script runs for
# the whole round (started early, detached), probes the tunnel every ~8
# minutes with a hard subprocess timeout (a hung tunnel blocks the
# probing process — never probe in-process), and on the first live
# window runs the FULL bench payload:
#
#   1. warm   — bench.py at 2M rows: populates .jax_cache with the exact
#               driver programs (first remote compiles cost 20-220s each)
#   2. main   — bench.py default (8M rows, q1 + join + window shapes)
#   3. suite  — bench.py --suite (scale rig, all query shapes)
#
# Each run's stdout (one JSON line per result) is saved under
# .bench_capture/run_<ts>_<mode>.out.  bench.py replays the freshest
# platform:"tpu" capture when the driver invokes it on a dead tunnel —
# see _load_capture() there.
#
# Re-captures on later windows (fresher numbers from an improved engine
# beat stale ones) but not more than once per 2h, and never twice
# concurrently.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
CAP="$REPO/.bench_capture"
LOG=/tmp/tunnel_status.log
mkdir -p "$CAP"

while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  # a dead tunnel can also fail FAST (plugin init error) with jax
  # silently falling back to the CPU platform — that must not count as
  # ALIVE, so assert the default backend is the device one ("axon")
  out=$(cd /tmp && timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() != 'cpu', 'cpu fallback'
print('ALIVE', float(jnp.sum(jnp.ones(8))))" 2>/dev/null | grep ALIVE)
  if [ -n "$out" ]; then
    echo "$ts ALIVE" >> "$LOG"
    # clear a stale lock (a capture should never exceed ~4h)
    if [ -d "$CAP/capture_running" ] && \
       [ $(( $(date +%s) - $(stat -c %Y "$CAP/capture_running") )) -gt 14400 ]; then
      rmdir "$CAP/capture_running" 2>/dev/null
    fi
    recent_done=0
    if [ -f "$CAP/capture_done" ] && \
       [ $(( $(date +%s) - $(stat -c %Y "$CAP/capture_done") )) -lt 7200 ]; then
      recent_done=1
    fi
    # mkdir is the test-and-set in one syscall: two watcher instances
    # hitting the same ALIVE tick must not run two payloads against the
    # one chip (contended numbers would be banked as official evidence)
    if [ "$recent_done" = 0 ] && mkdir "$CAP/capture_running" 2>/dev/null; then
      (
        cd "$REPO"
        cycle_files=""
        # main FIRST: .jax_cache already holds the warm programs from
        # earlier windows, and tunnel windows can be short — the 8M-row
        # headline number must not wait behind a warm-up run
        for mode in main warm suite; do
          ts2=$(date -u +%Y-%m-%dT%H:%M:%SZ)
          echo "$ts2 capture $mode start" >> "$LOG"
          # bank the run's telemetry (metrics exposition + doctor
          # verdict, pid-stamped — see bench._bank_telemetry) beside
          # the capture so each banked number carries its diagnosis
          export SRT_BENCH_TELEMETRY_DIR="$CAP/telemetry_${ts2}_${mode}"
          case $mode in
            main)  BENCH_BUDGET_S=1800 timeout 1900 \
                     python bench.py ;;
            warm)  BENCH_BUDGET_S=1200 timeout 1300 \
                     python bench.py 2000000 ;;
            suite) BENCH_BUDGET_S=3600 timeout 3700 \
                     python bench.py --suite ;;
          esac > "$CAP/run_${ts2}_${mode}.out" \
              2> "$CAP/run_${ts2}_${mode}.err"
          unset SRT_BENCH_TELEMETRY_DIR
          cycle_files="$cycle_files $CAP/run_${ts2}_${mode}.out"
          echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) capture $mode done" >> "$LOG"
        done
        # leak-sentinel soak on the SAME live window (ISSUE 14): steady
        # dispatch/memory behaviour on-chip is evidence the coalescer and
        # fused probe don't leak buffers across queries.  Short and last
        # — the bench numbers above must never wait behind a soak.
        ts3=$(date -u +%Y-%m-%dT%H:%M:%SZ)
        echo "$ts3 capture soak start" >> "$LOG"
        timeout 700 python tools/leak_sentinel.py --seconds 600 \
            --tenants 2 --rows 8000 \
            --out "$CAP/soak_${ts3}.json" \
            > "$CAP/soak_${ts3}.out" 2> "$CAP/soak_${ts3}.err"
        echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) capture soak done" >> "$LOG"
        # stamp capture_done ONLY if this cycle banked a record that
        # bench.py's replay will actually accept (the SAME predicate —
        # bench._usable_capture_record — so the two can never drift); a
        # fruitless cycle must not suppress re-capture at the next window
        if SRT_CYCLE_FILES="$cycle_files" JAX_PLATFORMS=cpu \
           python - <<'PYEOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
import bench
ok = False
for path in os.environ["SRT_CYCLE_FILES"].split():
    try:
        for line in open(path):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if bench._usable_capture_record(r):
                ok = True
    except OSError:
        pass
sys.exit(0 if ok else 1)
PYEOF
        then
          date -u +%Y-%m-%dT%H:%M:%SZ > "$CAP/capture_done"
        else
          echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) capture cycle banked no on-chip record" >> "$LOG"
        fi
        rmdir "$CAP/capture_running" 2>/dev/null
      ) &
    fi
  else
    echo "$ts dead" >> "$LOG"
  fi
  sleep 480
done
